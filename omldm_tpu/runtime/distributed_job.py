"""Multi-process streaming deployment: N ingest partitions, one global mesh.

Reference counterpart: the Flink job runs N parallel subtasks across a
cluster, fed by partitioned Kafka topics (reference: README.md:21-29,
parallelism 16 at src/main/scala/omldm/utils/DefaultJobParameters.scala:5),
and EVERY feature of the framework works in that deployment: many
concurrent pipelines (SpokeLogic.scala:28-29 keeps a Map[Int, wrapper] per
subtask), the full Create/Update/Query/Delete control plane
(PipelineMap.scala:37-57 broadcast to all workers), and checkpoint/restore
of operator state (FlinkSpoke.scala:233-334). The TPU-native deployment is
one PYTHON PROCESS per host, joined through ``jax.distributed``:

- each process owns an ingest partition (a strided slice of a shared file,
  or an assigned set of Kafka partitions — the role of Flink's per-subtask
  Kafka partition assignment, KafkaUtils.scala:11-31) and stages rows for
  its own mesh shard. The SINGLE-driver analogue of this striping is the
  sharded ingest plane (runtime/ingest_shard.py): there the stripes are
  byte-grid file chunks (chunk k -> worker k % N), the consumers are
  parser processes feeding ONE driver through shared-memory rings, and
  the driver's ascending-chunk replay keeps row order bit-identical to a
  single process — where this module's stripes feed N independent mesh
  shards and order is per-stripe;
- each batch is assembled into ONE globally-sharded array with
  ``host_local_array`` and trained by the standard :class:`SPMDTrainer`
  step — protocol sync is the same XLA collective whether the workers
  share a host or not (ICI within a slice, DCN across);
- the CONTROL PLANE lives on process 0: request lines are broadcast to
  every process over the collective fabric itself (a padded uint8 array,
  replicated-out jit) — control messages ride the same links as training
  traffic, no side channel. Every process hosts the same pipeline map
  (keyed by networkId, the multi-process form of SpokeLogic.scala:28-29);
  Create/Update deploy, Delete tears down, Query answers COLLECTIVELY
  (the union-holdout eval and the worker-0 parameter gather are lockstep
  programs) and process 0 emits the bucketed QueryResponse;
- statistics merge with psum-style reductions into the reference's
  JobStatistics schema (StatisticsOperator.scala:110-127) and process 0
  emits the report;
- checkpoints snapshot the SHARED fleet state once (gathered collectively,
  written by process 0) plus each process's partition cursor and local
  buffers, at synchronized pump points — restore resumes every process
  from the same consistent cut (the role of Flink's checkpoint barriers +
  FlinkSpoke.scala:233-334 operator state).

Single-process every piece degrades to local behavior, so the same code
runs a laptop test and a pod deployment. CLI (ParameterTool-style flags,
shared with ``python -m omldm_tpu``):

    python -m omldm_tpu \
        --coordinator 127.0.0.1:9876 --processes 2 --processId 0 \
        --requests reqs.jsonl --trainingData train.jsonl \
        --performanceOut perf.jsonl
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from omldm_tpu.api.requests import Request, RequestType
from omldm_tpu.api.responses import QueryResponse
from omldm_tpu.api.stats import JobStatistics, Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.control import PipelineManager
from omldm_tpu.runtime.databuffers import ArrayHoldout
from omldm_tpu.runtime.responses import ResponseMerger

CONTROL_CAP = 1 << 16  # fixed broadcast buffer: 64 KiB of request lines

# rows read from the source between synchronized pump points
CHUNK_ROWS = 4096


# --- elastic rescale-restore helpers (pure, unit-tested) -------------------


def rescale_shard_map(old_n: int, new_n: int, pid: int) -> List[int]:
    """Old-process checkpoint shards owned by NEW process ``pid`` when an
    ``old_n``-process snapshot restores across ``new_n`` processes: old
    shard q merges into survivor ``q % new_n`` — the distributed twin of
    the in-process shrink's ``id % n_new`` merge (StreamJob.rescale).
    Under grow this degenerates to identity for ``pid < old_n`` and the
    empty list for the seeded new processes; at ``old_n == new_n`` it is
    exactly ``[pid]`` (the pre-rescale restore path)."""
    return [q for q in range(old_n) if q % new_n == pid]


def _interleave_perm(lengths: Sequence[int]) -> List[int]:
    """Flat row indices that round-robin across blocks of the given
    lengths (block rows are laid out back to back): [b0[0], b1[0], ...,
    b0[1], b1[1], ...]. Merged per-process stripes stay a fair stream-
    order mix — the holdout/pending interleave of the in-process
    ``Spoke.absorb`` (SpokeLogic.scala:37-50 semantics)."""
    offsets = np.cumsum([0] + list(lengths))
    perm: List[int] = []
    for j in range(max(lengths, default=0)):
        for i, n in enumerate(lengths):
            if j < n:
                perm.append(int(offsets[i]) + j)
    return perm


def _interleave_rows(blocks: List[np.ndarray]) -> np.ndarray:
    """Round-robin row interleave of [n_i, ...] arrays (see
    :func:`_interleave_perm`)."""
    cat = np.concatenate(blocks)
    return cat[_interleave_perm([b.shape[0] for b in blocks])]


def _rescale_fleet_leaf(full: np.ndarray, key: str, dp_new: int) -> np.ndarray:
    """Redistribute one gathered fleet-state leaf (leading axis = the
    global dp worker rows) across a NEW worker-row count:

    - grow: new rows seed from the fleet model — a copy of worker row 0
      (the replica queries/evals read), exactly the in-process grow's
      seed-from-spoke-0; per-row accumulators that must not inflate the
      fleet totals (EF residuals, cum_loss) seed at zero instead;
    - shrink: retired row q merges into survivor ``q % dp_new`` — model
      state (params/preps) merges by group MEAN (rows are fed round-robin
      stripes, so equal weight is the faithful merge; the next protocol
      round would average them anyway), fleet-total accumulators
      (cum_loss) by group SUM, codec EF residuals reset (the model they
      were computed against is gone — the reset_streams analogue), and
      round-accounting counters (step/syncs/clock/accepted/est/...) keep
      the SURVIVOR row's own values so every surviving worker stays on
      the round schedule it checkpointed at."""
    dp_old = full.shape[0]
    if dp_new == dp_old:
        return full
    if dp_new > dp_old:
        if key in ("ef", "cum_loss"):
            extra = np.zeros((dp_new - dp_old,) + full.shape[1:], full.dtype)
        else:
            extra = np.repeat(full[:1], dp_new - dp_old, axis=0)
        return np.concatenate([full, extra], axis=0)
    if key in ("params", "preps"):
        return np.stack(
            [
                full[w::dp_new].mean(axis=0).astype(full.dtype)
                for w in range(dp_new)
            ]
        )
    if key == "cum_loss":
        return np.stack(
            [
                full[w::dp_new].sum(axis=0).astype(full.dtype)
                for w in range(dp_new)
            ]
        )
    if key == "ef":
        return np.zeros((dp_new,) + full.shape[1:], full.dtype)
    return full[:dp_new]


def _merge_cursors(cursors: List[Any]) -> Any:
    """One process's resume cursor from the per-process cursors of an
    N-process snapshot. Kafka cursors (``{"data": {...}, "requests":
    {...}}``) UNION across processes — the new partition stripe scatters
    old assignments across every new process, so each one needs the full
    per-partition offset map (max wins where a stale superset entry
    collides with the owner's newer value). File cursors (row ints /
    ``{"bytes", "lines"}`` dicts) are fleet-global and identical at a
    synchronized pump point, so the first shard speaks for everyone."""
    cursors = [c for c in cursors if c is not None]
    if not cursors:
        return None
    head = cursors[0]
    if isinstance(head, dict) and "data" in head:
        data: Dict[str, int] = {}
        requests: Dict[str, int] = {}
        for c in cursors:
            for k, v in (c.get("data") or {}).items():
                data[k] = max(int(v), data.get(k, 0))
            for k, v in (c.get("requests") or {}).items():
                requests[k] = max(int(v), requests.get(k, 0))
        return {"data": data, "requests": requests}
    return head


def _mesh_and_procs(coordinator, num_processes, process_id):
    """Join the process group (if any) and build the global dp mesh."""
    import jax

    from omldm_tpu.parallel.multihost import initialize_multihost

    pid, nproc = initialize_multihost(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    from omldm_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(dp=n_dev, hub=1)
    return mesh, pid, nproc


def _atomic_write_bytes(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _atomic_write_json(path: str, obj: Any) -> None:
    _atomic_write_bytes(path, json.dumps(obj).encode("utf-8"))


def _atomic_savez(path: str, arrays: Dict[str, np.ndarray]) -> str:
    import hashlib
    import io

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    data = buf.getvalue()
    # through the fsync'd writer: the checkpoint barrier orders the
    # LATEST flip after these writes, but durability needs the fsync.
    # The sha256 of the bytes-as-written is returned so the snapshot
    # metadata can pin every file's content — restore verifies the
    # digests before trusting (or even loading) a generation.
    _atomic_write_bytes(path, data)
    return hashlib.sha256(data).hexdigest()


def _file_sha256(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class _DistPipeline:
    """One pipeline's state on THIS process — the per-subtask wrapper map
    entry (SpokeLogic.scala:28-29): the shared SPMD trainer plus this
    partition's holdout split, pending/forecast buffers and predictions."""

    def __init__(self, request: Request, raw_line: str, dim: int,
                 trainer, test_cap: int, stage_cap: int,
                 sparse: bool = False, max_nnz: int = 0):
        self.request = request
        self.raw_line = raw_line  # original JSON, for checkpoint manifests
        self.dim = dim
        self.trainer = trainer
        self.stage_cap = stage_cap
        # sparse (padded-COO) pipelines buffer (idx, val) row pairs — the
        # reference's SparseVector data model works in its cluster
        # deployment too (DataPointParser.scala:4,20-47)
        self.sparse = sparse
        self.max_nnz = max_nnz
        if sparse:
            from omldm_tpu.runtime.databuffers import SparseHoldout

            self.test_set = SparseHoldout(test_cap, max_nnz)
        else:
            self.test_set = ArrayHoldout(test_cap, dim)
        self.holdout_count = 0
        self.pend_x: List[np.ndarray] = []   # dense rows, or COO idx
        self.pend_v: List[np.ndarray] = []   # COO val (sparse only)
        self.pend_y: List[np.ndarray] = []
        self.pend_n = 0
        self.fore_x: List[np.ndarray] = []   # dense rows, or COO idx
        self.fore_v: List[np.ndarray] = []   # COO val (sparse only)
        self.fore_n = 0
        self.predictions: List[float] = []
        self.steps_run = 0
        # pump-granularity learning curve: (global mean loss of the pump's
        # last step, cumulative GLOBAL rows staged) — the distributed form
        # of the PS's incremental curve slices (FlinkHub.scala:101-116)
        self.curve: List[Tuple[float, int]] = []
        self.global_rows = 0
        # cached per-pipeline jitted collective programs
        self._eval_jit = None
        self._predict_jit = None
        self._accepted_jit = None
        self._gather_params_jit = None
        self._gather_state_jit = None
        self._counters_jit = None


class DistributedStreamJob:
    """Streaming pipelines trained across every process's devices.

    The training contract mirrors the in-process SPMD bridge: 8-of-10
    holdout split per partition (FlinkSpoke.scala:94-104 semantics, applied
    to the partition the way each Flink subtask applies it to its own
    split), staged [local_dp, B, D] micro-batches, one collective step per
    full stage across ALL processes in lockstep. Every collective-bearing
    method must be called at synchronized points with identical arguments
    on every process (request lines are broadcast to guarantee this)."""

    def __init__(
        self,
        config: JobConfig,
        coordinator: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ):
        import jax

        self.config = config
        self.mesh, self.pid, self.nproc = _mesh_and_procs(
            coordinator, num_processes, process_id
        )
        self._jax = jax
        self.dp_global = self.mesh.shape["dp"]
        self.dp_local = max(self.dp_global // self.nproc, 1)
        self.pipeline_manager = PipelineManager()
        self.pipelines: Dict[int, _DistPipeline] = {}
        self.dim: Optional[int] = None  # stream width, set by first deploy
        self.hash_dims = 0  # trailing hashed-categorical slots within dim
        self.stream_mode: Optional[str] = None  # "dense"|"sparse", pinned
        self.sparse_hash_space = 0  # COO hashed tail width (sparse mode)
        self.responses: List[QueryResponse] = []
        self.response_merger = ResponseMerger(self.responses.append)
        self.orphan_predictions: List[Tuple[int, float]] = []
        # liveness callback invoked mid-deploy: a fleet-scale Create
        # wave (or a restore redeploying it) constructs pipelines for
        # far longer than a heartbeat window, and a worker that is
        # provably alive must not read as beat-silent
        self.beat_hook: Optional[Callable[[], None]] = None
        # per-pipeline collective programs shared across pipelines whose
        # trainers agree on the full static signature — one compiled
        # executable per CONFIG, not per pipeline (the fleet-scale mmap
        # budget; parallel.spmd shares the step programs the same way)
        self._prog_cache: Dict[tuple, Any] = {}
        self.start_time = time.time()
        # overload control (runtime/overload.py; --overload / JobConfig):
        # on the distributed engine the honest backlog signal is the
        # host-side staging (pending/forecast buffers + SSP-requeued
        # rows), and the action is SOURCE BACKPRESSURE — _drive_kafka
        # pauses this process's data partitions while the backlog is past
        # backlogCritical. None (default) = unarmed, zero-cost.
        from omldm_tpu.runtime.overload import parse_overload_spec

        self.overload_cfg = parse_overload_spec(
            getattr(config, "overload", "") or ""
        )
        # pressure PEAK since the last heartbeat tick: the drive loops pump
        # (drain) right before each tick, so the instantaneous level at
        # tick time would always read OK — the peak over the window is the
        # honest signal the autoscaling supervisor consumes (updated by
        # the row-buffering paths, zero-cost unarmed)
        self._level_window = 0
        # elastic rescale-restore (restore-with-rescale): a snapshot taken
        # with N processes may restore across M != N (fleet rows merged/
        # seeded, shards remapped, source stripe re-agreed). Disabled via
        # --rescaleRestore false, which degrades a count mismatch to a
        # warned fresh start instead of crashing the fleet attempt.
        self.rescale_restore = True
        # cumulative rescale count for Statistics: pinned by the
        # supervisor (--rescaleCount, authoritative across incarnations);
        # an unsupervised manual rescale-restore self-increments instead
        self.rescales_performed = 0
        self._rescale_count_pinned = False
        # self-healing fleet telemetry (runtime/selfheal.py): how many
        # process slots the supervisor has shrunk away from the configured
        # width (--fleetDegraded, authoritative; 0 = full width), and the
        # count of telemetry writes (heartbeat files, black-box ring
        # dumps) the disk refused — survived as a dropped-write counter
        # instead of a dead worker (blackboxWriteErrors)
        self.fleet_degraded = 0
        self.hb_write_errors = 0
        # collective hang watchdog (--collectiveTimeoutMs; None = unarmed,
        # zero objects): a worker stuck in a fabric collective whose peer
        # died dumps its black box and exits HANG_EXIT instead of wedging
        self.watchdog = None
        self._ckpt_seq = 0
        self._reduce_jits: Dict[Tuple[str, int], Any] = {}
        self._loss_mean_jit = None
        # serving-launch wall clock (per collective predict round,
        # including the device wait): recent_p99 rides the heartbeat
        # frame to the autoscaling supervisor — the host-plane latency
        # signal the staging-backlog level alone cannot see
        from omldm_tpu.utils.tracing import StepTimer

        self.serve_timer = StepTimer("dist_serve", cap=8192)
        # flight recorder (runtime/events.py; --events / --blackboxPath):
        # the distributed engine keeps the JOURNAL half of the plane —
        # restore/rescale/backpressure decisions record as typed events,
        # the ring dumps to blackbox-proc<pid>.jsonl at every dirty chunk
        # tick (so a SIGKILLed worker leaves a near-current ring for the
        # supervisor's incident bundle) — while the watchdog rule layer
        # stays host-plane (it reads the in-process metrics registry).
        # None (default) = zero recorder objects.
        from omldm_tpu.runtime.events import EventJournal, parse_events_spec

        self.events = None
        self._ev_clock = 0  # records consumed (the journal's count clock)
        ev_cfg = parse_events_spec(getattr(config, "events", "") or "")
        if ev_cfg is not None:
            self.events = EventJournal(
                cap=ev_cfg.cap,
                pid=self.pid,
                path=(
                    ev_cfg.blackbox_path
                    or getattr(config, "blackbox_path", "")
                ),
                position=lambda: self._ev_clock,
                tail_len=ev_cfg.tail,
            )

    def _warn(self, msg: str) -> None:
        print(f"[distributed p{self.pid}] {msg}", file=sys.stderr)

    def _record_event(self, kind: str, cause: str, **fields) -> None:
        """Flight-recorder hook: one attribute read when unarmed."""
        if self.events is not None:
            self.events.record(kind, cause, **fields)

    # --- hang safety (runtime/selfheal.HangWatchdog) ---

    def hang_guard(self, phase: str):
        """Deadline guard around a collective-bearing region: re-entrant,
        refreshed on every entry. The no-op context when the watchdog is
        unarmed (the default)."""
        if self.watchdog is None:
            from contextlib import nullcontext

            return nullcontext()
        return self.watchdog.guard(phase)

    def arm_hang_watchdog(
        self, timeout_s: float, warmup_s: Optional[float] = None
    ) -> None:
        """Arm the collective watchdog: a guarded region that makes no
        progress for ``timeout_s`` (first entry per phase: ``warmup_s``,
        the cold-compile allowance) dumps this process's black box and
        exits :data:`~omldm_tpu.runtime.selfheal.HANG_EXIT` — the
        reason-coded "my peer is wedged" exit the supervisor blames on
        the SILENT process, not on this honest survivor."""
        from omldm_tpu.runtime.selfheal import HANG_EXIT, HangWatchdog

        def on_expire(phase: str) -> None:
            self._warn(
                f"collective watchdog: no progress in {phase!r} for "
                f"{timeout_s * 1000.0:.0f}ms — a peer is dead or wedged; "
                f"dumping black box and exiting HANG_EXIT({HANG_EXIT}) "
                "instead of blocking forever"
            )
            if self.events is not None:
                from omldm_tpu.runtime.events import HANG

                self.events.record(
                    HANG, "collective_timeout", phase=phase,
                    timeout_ms=timeout_s * 1000.0,
                )
                self.events.incident("hang")
            os._exit(HANG_EXIT)

        self.watchdog = HangWatchdog(
            timeout_s, on_expire, warmup_s=warmup_s
        )

    def note_event_records(self, n: int) -> None:
        """Advance the journal's count clock (records consumed this
        incarnation) — called from the chunk tick."""
        if self.events is not None:
            self._ev_clock += int(n)

    # --- overload control (runtime/overload.py) ---

    def backlog_rows(self) -> int:
        """Host-side staging backlog on THIS process: rows buffered ahead
        of the collective step (pending + forecast buffers) plus rows the
        SSP bound refused and requeued."""
        return int(sum(
            p.pend_n + p.fore_n + getattr(p.trainer, "requeued_rows", 0)
            for p in self.pipelines.values()
        ))

    def overload_level(self) -> int:
        """0 OK / 1 ELEVATED / 2 CRITICAL from the staging backlog (the
        distributed engine's pressure signal); 0 when unarmed."""
        cfg = self.overload_cfg
        if cfg is None:
            return 0
        backlog = self.backlog_rows()
        if backlog >= cfg.backlog_critical:
            return 2
        if backlog >= cfg.backlog_high:
            return 1
        return 0

    def _note_pressure(self) -> None:
        """Track the pressure peak across a pump window (called by the
        row-buffering paths — the moment the staging backlog is honest,
        before pump drains it). One attribute write when unarmed-free."""
        if self.overload_cfg is not None:
            level = self.overload_level()
            if level > self._level_window:
                self._level_window = level

    def overload_level_window(self) -> int:
        """The worst pressure level since the last call (folded with the
        instantaneous level), then reset — the per-tick value the
        heartbeat file carries to the autoscaling supervisor."""
        level = max(self._level_window, self.overload_level())
        self._level_window = 0
        return level

    def heartbeat_frame(self) -> dict:
        """The compact metrics frame this worker's heartbeat file carries
        (supervisor._beat_frame parses it): the window-peak pressure
        level plus the signals the level derivation alone cannot
        express — collective-predict serve p99 ms and the staging
        backlog row count. ``imbalance`` is 0 here: the distributed
        engine fans every record to every pipeline, so per-tenant
        fair-share excess is a host-plane (Spoke) signal — the key stays
        in the frame so one supervisor parser serves both planes."""
        return {
            "level": self.overload_level_window(),
            "serveP99": round(self.serve_timer.recent_p99(), 3),
            "imbalance": 0.0,
            "backlog": int(self.backlog_rows()),
            # flight-recorder high-water id + alert count (0 unarmed; the
            # alert half lives on the host plane, so alerts stays 0 here
            # — the key rides the frame so one supervisor parser serves
            # both planes, like imbalance)
            "events": (
                self.events.high_water if self.events is not None else 0
            ),
            "alerts": (
                self.events.alerts if self.events is not None else 0
            ),
        }

    def _fetch_replicated(self, arr) -> np.ndarray:
        """Host copy of a REPLICATED global array: read the local shard
        (a plain device_get would try to fetch non-addressable shards of
        the multi-process array and fail)."""
        return np.asarray(arr.addressable_shards[0].data)

    # --- fabric primitives ---

    def _collective_reduce(self, values: Sequence[float], op: str) -> np.ndarray:
        """Elementwise sum/max of a small per-process float vector over the
        fabric; returns the reduced vector (identical on every process)."""
        vec = np.asarray(list(values), np.float64)
        if self.nproc == 1:
            return vec
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        k = vec.size
        if op == "sum":
            rows = np.broadcast_to(
                vec[None, :] / self.dp_local, (self.dp_local, k)
            ).astype(np.float64)
        else:
            rows = np.broadcast_to(vec[None, :], (self.dp_local, k)).astype(
                np.float64
            )
        arr = host_local_array(rows, self.mesh, P("dp"))
        fn = self._reduce_jits.get((op, k))
        if fn is None:
            rep = NamedSharding(self.mesh, P())
            reduce = (lambda a: a.sum(axis=0)) if op == "sum" else (
                lambda a: a.max(axis=0)
            )
            fn = jax.jit(reduce, out_shardings=rep)
            self._reduce_jits[(op, k)] = fn
        # every completed reduce is fleet progress: the (re-entrant) guard
        # entry refreshes any outer phase's hang deadline
        with self.hang_guard("reduce"):
            return self._fetch_replicated(fn(arr))

    def _agree_rounds(self, local_rounds: int) -> int:
        """All processes take the MAX of their desired round counts over
        the fabric, so every one of them enters the same number of
        collective steps (short partitions contribute masked batches)."""
        return int(self._collective_reduce([float(local_rounds)], "max")[0])

    def barrier(self) -> None:
        """Fabric barrier (a fetched 1-scalar collective): nobody returns
        until every process reached this point."""
        self._collective_reduce([0.0], "max")

    # --- control plane: process-0 broadcast over the fabric ---

    # frame header: 4-byte payload length + 1-byte continuation flag
    _FRAME_HEADER = 5

    def _frame_batches(self, lines: List[str]) -> List[List[str]]:
        """Greedy-pack request lines into frames that fit the fixed
        broadcast buffer (a fleet-scale Create wave — tens of thousands
        of tenants — is far larger than one frame)."""
        cap = CONTROL_CAP - self._FRAME_HEADER
        batches: List[List[str]] = [[]]
        size = 0
        for line in lines:
            n = len(line.encode("utf-8"))
            if n > cap:
                raise ValueError(
                    f"request line too large for the control broadcast "
                    f"({n} bytes > {cap})"
                )
            if batches[-1] and size + 1 + n > cap:
                batches.append([])
                size = 0
            size += n + (1 if len(batches[-1]) else 0)
            batches[-1].append(line)
        return batches

    def _broadcast_lines(self, lines: List[str]) -> List[str]:
        """Every process receives process 0's request lines. Each frame
        travels as a [nproc, CONTROL_CAP] uint8 array assembled from
        per-process rows; a replicated-output jit hands every process row
        0 — i.e. the broadcast IS a collective on the training fabric.
        Payloads larger than one frame stream as multiple frames, paced
        by a continuation flag in the header: every process loops until
        process 0's flag clears, so the collective count stays lockstep
        without anybody knowing the total up front."""
        out: List[str] = []
        batches = self._frame_batches(lines) if self.pid == 0 else [[]]
        i = 0
        while True:
            batch = batches[i] if i < len(batches) else []
            more = self.pid == 0 and i + 1 < len(batches)
            received, more = self._broadcast_frame(batch, more)
            out.extend(received)
            i += 1
            if not more:
                return out

    def _broadcast_frame(
        self, lines: List[str], more: bool
    ) -> Tuple[List[str], bool]:
        """One fixed-size broadcast collective; returns (lines, more) as
        decoded from process 0's row."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        payload = "\n".join(lines).encode("utf-8") if self.pid == 0 else b""
        hdr = self._FRAME_HEADER
        if len(payload) > CONTROL_CAP - hdr:
            raise ValueError(
                f"control broadcast overflow ({len(payload)} bytes > "
                f"{CONTROL_CAP - hdr}); split the request batch"
            )
        row = np.zeros((1, CONTROL_CAP), np.uint8)
        row[0, :4] = np.frombuffer(
            np.uint32(len(payload)).tobytes(), np.uint8
        )
        row[0, 4] = 1 if more else 0
        row[0, hdr : hdr + len(payload)] = np.frombuffer(payload, np.uint8)
        if self.nproc == 1:
            rows = row
        else:
            # one row per process on the dp axis; replicated output makes
            # row 0 locally addressable everywhere
            mesh_rows = np.repeat(row, self.dp_local, axis=0)
            arr = host_local_array(mesh_rows, self.mesh, P("dp"))
            take0 = jax.jit(
                lambda a: a[0],
                out_shardings=NamedSharding(self.mesh, P()),
            )
            rows = self._fetch_replicated(take0(arr))[None, :]
        n = int(np.frombuffer(rows[0, :4].tobytes(), np.uint32)[0])
        text = rows[0, hdr : hdr + n].tobytes().decode("utf-8")
        return [l for l in text.split("\n") if l], bool(rows[0, 4])

    def sync_requests(self, lines: Optional[List[str]] = None) -> None:
        """Process 0 passes its pending request lines; every process runs
        the SAME control-plane transitions afterwards (the broadcast makes
        the lines identical, so the collective programs Query/Delete/Create
        trigger stay lockstep). The full request vocabulary is honored:
        Create/Update deploy, Delete tears down, Query answers collectively;
        anything invalid or unsupported is LOGGED and dropped, never
        silently ignored (PipelineMap.scala:34,46 prints and drops)."""
        with self.hang_guard("control"):
            self._sync_requests_guarded(lines)

    def _deploy_beat(self, i: int) -> None:
        if self.beat_hook is not None and i % 256 == 255:
            self.beat_hook()

    def _shared_jit(self, p: "_DistPipeline", name: str, build):
        key = (name, p.sparse, p.dim, p.trainer.program_key)
        fn = self._prog_cache.get(key)
        if fn is None:
            fn = self._prog_cache[key] = build()
        return fn

    def _sync_requests_guarded(
        self, lines: Optional[List[str]] = None
    ) -> None:
        for i, line in enumerate(self._broadcast_lines(list(lines or []))):
            self._deploy_beat(i)
            request = Request.from_json(line)
            if request is None:
                self._warn(f"dropping unparseable request line: {line[:120]!r}")
                continue
            err = self.pipeline_manager.validate(request)
            if err is not None:
                self._warn(
                    f"rejecting {request.request.value} for pipeline "
                    f"{request.id}: {err}"
                )
                continue
            if request.request in (RequestType.CREATE, RequestType.UPDATE):
                self._deploy(request, line)
            elif request.request == RequestType.DELETE:
                self.pipeline_manager.admit(request)
                dropped = self.pipelines.pop(request.id, None)
                if dropped is not None:
                    # predictions already served belong to the output even
                    # though the pipeline is gone (a streaming sink would
                    # have emitted them long ago)
                    self.orphan_predictions.extend(
                        (request.id, v) for v in dropped.predictions
                    )
                self._warn(f"pipeline {request.id} deleted")
            elif request.request == RequestType.QUERY:
                self._answer_query(request)

    def _request_dim(self, request: Request) -> Optional[int]:
        ds = request.learner.data_structure if request.learner else None
        if ds and "nFeatures" in ds:
            return int(ds["nFeatures"]) + int(
                request.training_configuration.extra.get("hashDims", 0)
            )
        return None

    def _deploy(self, request: Request, raw_line: str) -> None:
        """Deploy/replace one pipeline on the shared mesh. The distributed
        runtime hosts MANY concurrent pipelines (the reference's per-subtask
        Map[Int, wrapper], SpokeLogic.scala:28-29); all share the stream, so
        their feature widths must agree with the stream width pinned by the
        first deploy. Anything the collective engine cannot host (sparse
        COO streams, host-side learners, unsupported protocols) is rejected
        WITH a logged reason instead of dropped silently."""
        from omldm_tpu.api.requests import TrainingConfiguration
        from omldm_tpu.parallel.spmd import SPMDTrainer

        ds = (request.learner.data_structure if request.learner else None) or {}
        sparse = bool(ds.get("sparse"))
        if self.stream_mode is not None and (
            (self.stream_mode == "sparse") != sparse
        ):
            self._warn(
                f"rejecting pipeline {request.id}: the stream is "
                f"{self.stream_mode} (pinned by the first deploy) and a "
                f"{'sparse' if sparse else 'dense'} pipeline cannot share "
                "its parse route"
            )
            return
        if sparse:
            # sparse widths are EXACT (hashSpace inside nFeatures); the
            # dense hashDims knob does not apply to the COO path
            dim = int(ds.get("nFeatures", 0)) or None
        else:
            dim = self._request_dim(request)
        if dim is None:
            self._warn(
                f"rejecting pipeline {request.id}: distributed deployment "
                "needs dataStructure.nFeatures on the Create (the stream "
                "width must be known before partitions start)"
            )
            return
        if self.dim is not None and dim != self.dim:
            self._warn(
                f"rejecting pipeline {request.id}: feature width {dim} != "
                f"stream width {self.dim} pinned by the first deploy"
            )
            return
        tc = request.training_configuration or TrainingConfiguration(
            protocol="Synchronous"
        )
        try:
            trainer = SPMDTrainer(
                request.learner,
                request.preprocessors or (),
                dim=dim,
                protocol=tc.protocol,
                mesh=self.mesh,
                training_configuration=tc,
                batch_size=self.config.batch_size,
            )
        except ValueError as exc:
            self._warn(f"rejecting pipeline {request.id}: {exc}")
            return
        hash_dims = 0 if sparse else int(tc.extra.get("hashDims", 0))
        if self.dim is not None and hash_dims != self.hash_dims:
            self._warn(
                f"rejecting pipeline {request.id}: hashDims {hash_dims} != "
                f"stream hashDims {self.hash_dims} pinned by the first deploy"
            )
            return
        max_nnz = int(ds.get("maxNnz", 40)) if sparse else 0
        hash_space = int(ds.get("hashSpace", 0)) if sparse else 0
        if sparse and self.pipelines:
            pinned = next(iter(self.pipelines.values())).max_nnz
            if max_nnz != pinned or hash_space != self.sparse_hash_space:
                self._warn(
                    f"rejecting pipeline {request.id}: COO layout "
                    f"(maxNnz {max_nnz}, hashSpace {hash_space}) differs "
                    "from the stream layout pinned by the first deploy"
                )
                return
        self.pipeline_manager.admit(request)
        self.dim = dim
        self.hash_dims = hash_dims
        self.stream_mode = "sparse" if sparse else "dense"
        if sparse:
            self.sparse_hash_space = hash_space
        if request.id in self.pipelines:
            self._warn(
                f"pipeline {request.id} replaced by "
                f"{request.request.value} (fresh model state)"
            )
        self.pipelines[request.id] = _DistPipeline(
            request, raw_line, dim, trainer,
            self.config.test_set_size,
            self.dp_local * self.config.batch_size,
            sparse=sparse, max_nnz=max_nnz,
        )
        if self.watchdog is not None:
            # a fresh pipeline means fresh XLA compiles in already-warmed
            # phases: re-grant the cold-compile allowance so the hang
            # watchdog does not shoot an honestly-compiling worker
            self.watchdog.rewarm()

    # --- data path: this process's partition only ---

    def handle_partition_rows(self, x: np.ndarray, y: np.ndarray) -> None:
        """Buffer rows from THIS process's ingest partition for EVERY live
        pipeline (each record reaches each pipeline, FlinkSpoke's per-key
        fan-out), holdout-split per pipeline exactly as the in-process
        runtime applies it per worker. Rows are NOT trained here:
        collective steps only run inside :meth:`pump`, where every process
        agrees on the round count first — a process stepping on local
        buffer fullness alone could enter a collective its peers never
        reach (lockstep deadlock)."""
        n = x.shape[0]
        if n == 0:
            return
        for p in self.pipelines.values():
            self._buffer_rows(p, x, y)
        self._note_pressure()

    def _buffer_rows(self, p: _DistPipeline, x: np.ndarray, y: np.ndarray) -> None:
        if self.config.test:
            n = x.shape[0]
            c = (p.holdout_count + np.arange(n)) % 10
            p.holdout_count += n
            test_mask = c >= 8
            keep_idx = np.nonzero(~test_mask)[0]
            t_idx = np.nonzero(test_mask)[0]
            ev_x, ev_y, ev_src = p.test_set.append_many(x[t_idx], y[t_idx])
            if ev_src.size:
                pos = np.concatenate([keep_idx, t_idx[ev_src]])
                order = np.argsort(pos, kind="stable")
                x = np.concatenate([x[keep_idx], ev_x])[order]
                y = np.concatenate([y[keep_idx], ev_y])[order]
            else:
                x, y = x[keep_idx], y[keep_idx]
        else:
            p.holdout_count += x.shape[0]
        if x.shape[0]:
            p.pend_x.append(np.asarray(x, np.float32))
            p.pend_y.append(np.asarray(y, np.float32))
            p.pend_n += x.shape[0]

    def handle_partition_rows_sparse(
        self, idx: np.ndarray, val: np.ndarray, y: np.ndarray
    ) -> None:
        """COO twin of :meth:`handle_partition_rows` (padded (idx, val)
        rows from this partition, holdout-split per pipeline)."""
        if idx.shape[0] == 0:
            return
        for p in self.pipelines.values():
            self._buffer_rows_sparse(p, idx, val, y)
        self._note_pressure()

    def _buffer_rows_sparse(self, p, idx, val, y) -> None:
        if self.config.test:
            n = idx.shape[0]
            c = (p.holdout_count + np.arange(n)) % 10
            p.holdout_count += n
            test_mask = c >= 8
            keep = np.nonzero(~test_mask)[0]
            t_idx = np.nonzero(test_mask)[0]
            ev_i, ev_v, ev_y, ev_src = p.test_set.append_many(
                idx[t_idx], val[t_idx], y[t_idx]
            )
            if ev_src.size:
                pos = np.concatenate([keep, t_idx[ev_src]])
                order = np.argsort(pos, kind="stable")
                idx = np.concatenate([idx[keep], ev_i])[order]
                val = np.concatenate([val[keep], ev_v])[order]
                y = np.concatenate([y[keep], ev_y])[order]
            else:
                idx, val, y = idx[keep], val[keep], y[keep]
        else:
            p.holdout_count += idx.shape[0]
        if idx.shape[0]:
            p.pend_x.append(np.asarray(idx, np.int32))
            p.pend_v.append(np.asarray(val, np.float32))
            p.pend_y.append(np.asarray(y, np.float32))
            p.pend_n += idx.shape[0]

    def handle_forecast_rows_sparse(
        self, idx: np.ndarray, val: np.ndarray
    ) -> None:
        if idx.shape[0] == 0:
            return
        for p in self.pipelines.values():
            p.fore_x.append(np.asarray(idx, np.int32))
            p.fore_v.append(np.asarray(val, np.float32))
            p.fore_n += idx.shape[0]
        self._note_pressure()

    def handle_forecast_rows(self, x: np.ndarray) -> None:
        """Buffer forecast rows from this partition for every pipeline;
        predictions are served collectively at the next :meth:`pump` (the
        model is sharded across processes, so serving is a lockstep
        program like everything else)."""
        if x.shape[0] == 0:
            return
        for p in self.pipelines.values():
            p.fore_x.append(np.asarray(x, np.float32))
            p.fore_n += x.shape[0]
        self._note_pressure()

    def pump(self, final: bool = False) -> None:
        """Run the agreed number of lockstep collective steps per pipeline
        over the buffered rows. Call at synchronized points of the drive
        loop (all processes pump after the same stream chunk; ``final=True``
        drains remainders with zero-masked padding). Pipelines are visited
        in sorted id order so every process issues the same collective
        sequence."""
        with self.hang_guard("pump"):
            for net_id in sorted(self.pipelines):
                p = self.pipelines[net_id]
                self._pump_pipeline(p, final)
                self._pump_forecasts(p)

    def _pump_pipeline(self, p: _DistPipeline, final: bool) -> None:
        cap = p.stage_cap
        want = -(-p.pend_n // cap) if final else p.pend_n // cap
        rounds = self._agree_rounds(int(want))
        if rounds == 0:
            return
        b = self.config.batch_size
        from jax.sharding import PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        width = p.max_nnz if p.sparse else p.dim
        buf_x = (
            np.concatenate(p.pend_x)
            if p.pend_x
            else np.zeros(
                (0, width), np.int32 if p.sparse else np.float32
            )
        )
        buf_v = (
            np.concatenate(p.pend_v)
            if p.sparse and p.pend_v
            else np.zeros((0, width), np.float32)
        )
        buf_y = (
            np.concatenate(p.pend_y)
            if p.pend_y
            else np.zeros((0,), np.float32)
        )
        p.pend_x, p.pend_v, p.pend_y = [], [], []
        requeued = []  # row blocks refused by the SSP bound this pump
        done = 0
        staged = 0
        last_loss = None
        for _ in range(rounds):
            rows = min(cap, buf_x.shape[0] - done)
            x = np.zeros(
                (cap, width), np.int32 if p.sparse else np.float32
            )
            v = np.zeros((cap, width), np.float32) if p.sparse else None
            y = np.zeros((cap,), np.float32)
            mask = np.zeros((cap,), np.float32)
            if rows > 0:
                x[:rows] = buf_x[done : done + rows]
                if p.sparse:
                    v[:rows] = buf_v[done : done + rows]
                y[:rows] = buf_y[done : done + rows]
                mask[:rows] = 1.0
            done += max(rows, 0)
            staged += max(rows, 0)
            x_d = host_local_array(
                x.reshape(self.dp_local, b, width), self.mesh, P("dp")
            )
            y_d = host_local_array(
                y.reshape(self.dp_local, b), self.mesh, P("dp")
            )
            m_d = host_local_array(
                mask.reshape(self.dp_local, b), self.mesh, P("dp")
            )
            if p.sparse:
                v_d = host_local_array(
                    v.reshape(self.dp_local, b, width), self.mesh, P("dp")
                )
                batch = (x_d, v_d)
            else:
                batch = x_d
            last_loss = p.trainer.step(
                batch, y_d, m_d, valid_count=max(rows, 0)
            )
            p.steps_run += 1
            if p.trainer.protocol == "SSP":
                self._requeue_refused(
                    p,
                    x.reshape(self.dp_local, b, width),
                    None if v is None else v.reshape(
                        self.dp_local, b, width
                    ),
                    y.reshape(self.dp_local, b),
                    mask.reshape(self.dp_local, b),
                    requeued,
                )
        # the trainer's internal curve holds lazy multi-process arrays the
        # host cannot np.asarray; the distributed curve below replaces it
        p.trainer._curve.clear()
        # rebuild the pending buffer from the un-stepped tail PLUS any
        # SSP-refused rows collected during the loop (overwriting with the
        # tail alone would silently drop the requeued rows)
        p.pend_x = [buf_x[done:]] if done < buf_x.shape[0] else []
        if p.sparse:
            p.pend_v = [buf_v[done:]] if done < buf_x.shape[0] else []
        p.pend_y = [buf_y[done:]] if done < buf_x.shape[0] else []
        p.pend_n = max(buf_x.shape[0] - done, 0)
        requeued_rows = 0
        for blk in requeued:
            p.pend_x.append(blk[0])
            if p.sparse:
                p.pend_v.append(blk[1])
            p.pend_y.append(blk[-1])
            p.pend_n += blk[0].shape[0]
            requeued_rows += blk[0].shape[0]
        # one pump-granularity learning-curve point: global mean loss of
        # the pump's last step + globally-consumed row count (two tiny
        # collectives per pump, not per step)
        if last_loss is not None:
            if self._loss_mean_jit is None:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P2

                self._loss_mean_jit = jax.jit(
                    lambda l: l.mean(),
                    out_shardings=NamedSharding(self.mesh, P2()),
                )
            loss_val = float(
                self._fetch_replicated(self._loss_mean_jit(last_loss))
            )
            consumed = self._collective_reduce(
                [float(staged - requeued_rows)], "sum"
            )[0]
            p.global_rows += int(consumed)
            p.curve.append((loss_val, p.global_rows))

    def _requeue_refused(self, p: _DistPipeline, xg, vg, yg, mg, requeued) -> None:
        """SSP pacing across processes: the device refuses batches of
        workers past the staleness bound (state untouched, accepted=0);
        each process collects ITS OWN refused rows into ``requeued`` (the
        caller merges them back into the pending buffer after the round
        loop) and corrects the fitted counter — the multi-process form of
        the SPMD bridge's host-driven requeue."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if p._accepted_jit is None:
            rep = NamedSharding(self.mesh, P())
            p._accepted_jit = self._shared_jit(
                p, "accepted",
                lambda: jax.jit(
                    lambda s: s["accepted"][:, 0] > 0.0, out_shardings=rep
                ),
            )
        acc = self._fetch_replicated(p._accepted_jit(p.trainer.state))
        lo = self.pid * self.dp_local
        mine = acc[lo : lo + self.dp_local]
        for w in np.nonzero(~mine)[0]:
            rows = mg[w] > 0.0
            k = int(rows.sum())
            if k == 0:
                continue
            p.trainer.note_requeued(k)
            if p.sparse:
                requeued.append((
                    np.asarray(xg[w][rows], np.int32),
                    np.asarray(vg[w][rows], np.float32),
                    np.asarray(yg[w][rows], np.float32),
                ))
            else:
                requeued.append((
                    np.asarray(xg[w][rows], np.float32),
                    np.asarray(yg[w][rows], np.float32),
                ))

    def _pump_forecasts(self, p: _DistPipeline) -> None:
        """Agreed rounds of collective predict over buffered forecast
        rows; every process appends ITS rows' predictions locally."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        cap = p.stage_cap
        rounds = self._agree_rounds(-(-p.fore_n // cap))
        if rounds == 0:
            return
        if p._predict_jit is None:
            t = p.trainer
            rep = NamedSharding(self.mesh, P())

            def w0(tree):
                return jax.tree_util.tree_map(lambda l: l[0, 0], tree)

            if p.sparse:

                def predict_fn(state, i, v):
                    k = i.shape[-1]
                    z = (i.reshape(-1, k), v.reshape(-1, k))
                    return t.learner.predict(w0(state["params"]), z)

            else:

                def predict_fn(state, x):
                    d = x.shape[-1]
                    z = x.reshape(-1, d)
                    for prep, s in zip(t.preps, state["preps"]):
                        z = prep.transform(w0(s), z)
                    return t.learner.predict(w0(state["params"]), z)

            p._predict_jit = self._shared_jit(
                p, "predict",
                lambda: jax.jit(predict_fn, out_shardings=rep),
            )
        width = p.max_nnz if p.sparse else p.dim
        buf = (
            np.concatenate(p.fore_x)
            if p.fore_x
            else np.zeros(
                (0, width), np.int32 if p.sparse else np.float32
            )
        )
        buf_v = (
            np.concatenate(p.fore_v)
            if p.sparse and p.fore_v
            else np.zeros((0, width), np.float32)
        )
        p.fore_x, p.fore_v, p.fore_n = [], [], 0
        done = 0
        for _ in range(rounds):
            rows = min(cap, buf.shape[0] - done)
            x = np.zeros(
                (cap, width), np.int32 if p.sparse else np.float32
            )
            if rows > 0:
                x[:rows] = buf[done : done + rows]
            x_d = host_local_array(
                x.reshape(self.dp_local, -1, width), self.mesh, P("dp")
            )
            if p.sparse:
                v = np.zeros((cap, width), np.float32)
                if rows > 0:
                    v[:rows] = buf_v[done : done + rows]
                v_d = host_local_array(
                    v.reshape(self.dp_local, -1, width), self.mesh, P("dp")
                )
                with self.serve_timer:
                    preds = self._fetch_replicated(p._predict_jit(
                        p.trainer.state, x_d, v_d
                    ))
            else:
                with self.serve_timer:
                    preds = self._fetch_replicated(p._predict_jit(
                        p.trainer.state, x_d
                    ))
            # the replicated output covers every process's rows; this
            # process's slice starts at pid * cap within the global batch
            mine = preds[self.pid * cap : self.pid * cap + max(rows, 0)]
            p.predictions.extend(float(v_) for v_ in mine)
            done += max(rows, 0)

    def flush(self) -> None:
        """Drain every pipeline, including SSP-requeued rows: repeated
        final pumps are guaranteed progress under balanced partitions (the
        bound refuses only workers ahead of the slowest, and every process
        keeps feeding its slowest workers); a livelock guard backstops
        pathological streams."""
        self.pump(final=True)
        with self.hang_guard("flush"):
            for net_id in sorted(self.pipelines):
                p = self.pipelines[net_id]
                guard = 0
                while self._agree_rounds(1 if p.pend_n else 0):
                    before = p.pend_n
                    self._pump_pipeline(p, final=True)
                    progressed = 1 if p.pend_n < before else 0
                    if not self._agree_rounds(progressed):
                        # NOBODY advanced: a dried-up partition pins the
                        # staleness bound (its worker's clock cannot move)
                        # — apply the termination-time release, exactly the
                        # host plane's SSPParameterServer.on_terminate
                        # semantics
                        p.trainer.release_stragglers()
                    guard += 1
                    if guard > 1000:
                        raise RuntimeError(
                            "SSP drain made no progress requeuing refused "
                            "rows"
                        )
                self._pump_forecasts(p)

    # --- queries ---

    def _answer_query(self, request: Request) -> None:
        """Answer a user Query COLLECTIVELY: the union-holdout eval and the
        worker-0 parameter gather are lockstep programs every process runs;
        process 0 assembles the bucketed QueryResponse fragments exactly as
        the SPMD bridge does (FlinkNetwork.scala:196-231 wire format; the
        fleet is one logical model, so the merger expects one fragment
        set)."""
        import jax
        import jax.flatten_util  # noqa: F401  (ravel_pytree inside the jit)
        from jax.sharding import NamedSharding, PartitionSpec as P

        p = self.pipelines.get(request.id)
        if p is None:
            # admitted by the gatekeeper but never deployed here (e.g. a
            # rejected sparse Create): say so instead of dropping
            self._warn(f"query for undeployed pipeline {request.id} dropped")
            return
        self._pump_pipeline(p, final=True)
        loss, score = self._evaluate_global(p)
        if p._gather_params_jit is None:
            rep = NamedSharding(self.mesh, P())

            def gather_fn(state):
                w0 = jax.tree_util.tree_map(lambda l: l[0, 0], state["params"])
                flat, _ = jax.flatten_util.ravel_pytree(w0)
                return flat

            p._gather_params_jit = self._shared_jit(
                p, "gather_params",
                lambda: jax.jit(gather_fn, out_shardings=rep),
            )
        flat = self._fetch_replicated(p._gather_params_jit(p.trainer.state))
        fitted = int(self._collective_reduce(
            [float(p.trainer.fitted)], "sum"
        )[0])
        if self.pid != 0:
            return
        rid = request.request_id if request.request_id is not None else 0
        bucket_cap = self.config.max_param_bucket_size
        chunks = [
            flat[i : i + bucket_cap]
            for i in range(0, max(flat.size, 1), bucket_cap)
        ] or [None]
        req = p.request
        learner_desc = {
            "name": req.learner.name,
            "hyperParameters": dict(req.learner.hyper_parameters or {}),
            "dataStructure": dict(req.learner.data_structure or {}),
        }
        self.response_merger.expect(rid, 1)
        for i, chunk in enumerate(chunks):
            learner = (
                dict(learner_desc) if i == 0 else {"name": learner_desc["name"]}
            )
            if chunk is not None:
                learner["parameters"] = {"bucketValues": chunk.tolist()}
            self.response_merger.add_fragment(
                QueryResponse(
                    response_id=rid,
                    mlp_id=req.id,
                    bucket=i,
                    num_buckets=len(chunks),
                    preprocessors=[
                        {
                            "name": pr.name,
                            "hyperParameters": dict(pr.hyper_parameters or {}),
                        }
                        for pr in (req.preprocessors or [])
                    ] if i == 0 else None,
                    learner=learner,
                    protocol=req.training_configuration.protocol if i == 0 else None,
                    data_fitted=fitted,
                    loss=loss,
                    score=score,
                    source_worker=0,
                )
            )

    # --- reporting ---

    def _evaluate_global(self, p: _DistPipeline) -> Tuple[float, float]:
        """Loss/score of the fleet model on the UNION of every process's
        holdout set, computed as ONE collective program: each process
        contributes its padded holdout as its mesh shard, the worker-0
        model is gathered inside the jit, and the masked means reduce
        globally — every process receives the same replicated scalars."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        cap = p.test_set.max_size
        width = p.max_nnz if p.sparse else p.dim
        xs_l = np.zeros(
            (self.dp_local, cap, width), np.int32 if p.sparse else np.float32
        )
        vs_l = (
            np.zeros((self.dp_local, cap, width), np.float32)
            if p.sparse else None
        )
        ys_l = np.zeros((self.dp_local, cap), np.float32)
        m_l = np.zeros((self.dp_local, cap), np.float32)
        n = len(p.test_set)
        if n:
            if p.sparse:
                ti, tv, ty = p.test_set.arrays()
                xs_l[0, :n] = ti
                vs_l[0, :n] = tv
                ys_l[0, :n] = ty
            else:
                xs, ys = p.test_set.arrays()
                xs_l[0, :n] = xs
                ys_l[0, :n] = ys
            m_l[0, :n] = 1.0
        x_d = host_local_array(xs_l, self.mesh, P("dp"))
        y_d = host_local_array(ys_l, self.mesh, P("dp"))
        m_d = host_local_array(m_l, self.mesh, P("dp"))
        v_d = (
            host_local_array(vs_l, self.mesh, P("dp")) if p.sparse else None
        )
        if p._eval_jit is None:
            t = p.trainer
            rep = NamedSharding(self.mesh, P())

            def w0(tree):
                return jax.tree_util.tree_map(lambda l: l[0, 0], tree)

            if p.sparse:

                def eval_fn(state, i, v, y, mask):
                    k = i.shape[-1]
                    z = (i.reshape(-1, k), v.reshape(-1, k))
                    yv = y.reshape(-1)
                    mv = mask.reshape(-1)
                    params = w0(state["params"])
                    return (
                        t.learner.loss(params, z, yv, mv),
                        t.learner.score(params, z, yv, mv),
                    )

            else:

                def eval_fn(state, x, y, mask):
                    d = x.shape[-1]
                    z = x.reshape(-1, d)
                    yv = y.reshape(-1)
                    mv = mask.reshape(-1)
                    for prep, s in zip(t.preps, state["preps"]):
                        z = prep.transform(w0(s), z)
                    params = w0(state["params"])
                    return (
                        t.learner.loss(params, z, yv, mv),
                        t.learner.score(params, z, yv, mv),
                    )

            p._eval_jit = self._shared_jit(
                p, "eval",
                lambda: jax.jit(eval_fn, out_shardings=(rep, rep)),
            )
        if p.sparse:
            loss, score = p._eval_jit(p.trainer.state, x_d, v_d, y_d, m_d)
        else:
            loss, score = p._eval_jit(p.trainer.state, x_d, y_d, m_d)
        return (
            float(self._fetch_replicated(loss)),
            float(self._fetch_replicated(score)),
        )

    def _global_device_counters(self, p: _DistPipeline) -> Tuple[int, int, int]:
        """(sum of per-worker syncs, worker-0 syncs, worker-0 steps) read
        through a replicated-output jit (the fleet state is sharded across
        processes; direct device_get cannot address remote shards)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if p._counters_jit is None:
            rep = NamedSharding(self.mesh, P())
            p._counters_jit = self._shared_jit(
                p, "counters",
                lambda: jax.jit(
                    lambda s: (
                        s["syncs"][:, 0].sum(),
                        s["syncs"][0, 0],
                        s["step"][0, 0],
                    ),
                    out_shardings=(rep, rep, rep),
                ),
            )
        a, b, c = p._counters_jit(p.trainer.state)
        return (
            int(self._fetch_replicated(a)),
            int(self._fetch_replicated(b)),
            int(self._fetch_replicated(c)),
        )

    def pipeline_statistics(self, p: _DistPipeline) -> Tuple[Statistics, int]:
        """One pipeline's Statistics (the reference schema,
        FlinkHub.scala:118-153) with fabric-reduced counters, plus the
        global holdout size. COLLECTIVE: every process must call it in the
        same order."""
        loss, score = self._evaluate_global(p)
        syncs_sum, syncs00, steps = self._global_device_counters(p)
        t = p.trainer
        sync_count, total_bytes = t.protocol_traffic_bytes(
            t.protocol, t.dp, t.flat_size, syncs_sum, syncs00, steps
        )
        # same counters priced at the configured transport codec's wire
        # width — the multi-process model-exchange route's bytes-on-wire
        # (the role of the reference's psMessages traffic accounting)
        _, wire_bytes = t.protocol_traffic_bytes(
            t.protocol, t.dp, t.flat_size, syncs_sum, syncs00, steps,
            codec=t.codec_name,
        )
        reduced = self._collective_reduce(
            [float(t.fitted), float(len(p.test_set)), float(p.pend_n)], "sum"
        )
        stats = Statistics(
            pipeline=p.request.id,
            protocol=t.protocol,
            models_shipped=sync_count * t.dp,
            bytes_shipped=int(total_bytes),
            bytes_on_wire=int(wire_bytes),
            num_of_blocks=sync_count,
            fitted=int(round(reduced[0])),
            learning_curve=[l for l, _ in p.curve],
            lcx=[r for _, r in p.curve],
            mean_buffer_size=float(reduced[2]) / self.nproc,
            score=score,
            # elastic-rescale telemetry: how many parallelism changes this
            # state has been carried across, and the CURRENT fleet width
            rescales_performed=self.rescales_performed,
            fleet_processes=self.nproc,
            # self-healing telemetry: slots shrunk away from the
            # configured width (supervisor-pinned gauge) and telemetry
            # writes the disk refused (heartbeats + black-box dumps)
            fleet_degraded=self.fleet_degraded,
            blackbox_write_errors=self.hb_write_errors + (
                self.events.write_errors if self.events is not None else 0
            ),
        )
        return stats, int(round(reduced[1]))

    def merged_report(self) -> Optional[dict]:
        """Global job report in the reference's JobStatistics schema
        (StatisticsOperator.scala:110-127): one Statistics entry per live
        pipeline, counters reduced over the fabric, score evaluated on the
        union holdout. COLLECTIVE — every process calls it; only process 0
        returns the dict (with deployment extras: process count, global
        holdout sizes, local SSP-requeue proof), the others get None."""
        entries = []
        holdout = {}
        requeued_local = 0
        with self.hang_guard("report"):
            for net_id in sorted(self.pipelines):
                p = self.pipelines[net_id]
                stats, hold = self.pipeline_statistics(p)
                entries.append(stats)
                holdout[str(net_id)] = hold
                requeued_local += getattr(p.trainer, "requeued_rows", 0)
        # terminate-time stranded-row accounting (collective: every
        # process contributes its staging backlog) — the SLO evaluator's
        # no-stranded-rows gate reads this instead of trusting the drive
        # loop to have drained
        stranded = self._collective_reduce(
            [float(self.backlog_rows())], "sum"
        )
        if self.pid != 0:
            return None
        report = JobStatistics(
            job_name=self.config.job_name,
            parallelism=self.dp_global,
            duration_ms=(time.time() - self.start_time) * 1000.0,
            statistics=entries,
        ).to_dict()
        report["processes"] = self.nproc
        # deployment-level mirrors of the per-pipeline gauges (operators
        # read the job header without walking statistics rows)
        report["fleetProcesses"] = self.nproc
        report["rescalesPerformed"] = self.rescales_performed
        # self-healing: slots currently shrunk away from the configured
        # width (0 = full width; supervisor-pinned via --fleetDegraded)
        report["fleetDegraded"] = self.fleet_degraded
        report["holdout"] = holdout
        # LOCAL count (process 0's workers): >0 proves the SSP requeue
        # path executed in this run
        report["requeuedLocal"] = requeued_local
        report["terminateAccounting"] = {
            "backlogRows": int(stranded[0]),
        }
        return report

    # --- checkpoint / restore (FlinkSpoke.scala:233-334 semantics) ---

    def save_checkpoint(self, root: str, cursor: Any) -> str:
        """Write a consistent distributed snapshot. Must be called at a
        synchronized pump point by EVERY process with its own ``cursor``
        (source position: row count for file striding, per-partition
        offsets for Kafka). Layout::

            root/ckpt-<k>/manifest.json     (proc 0: request lines, shape)
            root/ckpt-<k>/fleet_<net>.npz   (proc 0: gathered fleet state)
            root/ckpt-<k>/proc<p>.npz|.json (each: buffers + cursor)
            root/LATEST                     (proc 0: pointer, flipped last)

        The pointer flip happens only after a fabric barrier confirms every
        process's files are durable — the atomic-commit role of a Flink
        checkpoint barrier's acknowledgement. Every file's sha256 is
        recorded (fleet files in the manifest, each proc shard in its own
        cursor meta) so restore can verify a generation's INTEGRITY before
        trusting it — a torn/corrupted file fails the digest and the fleet
        falls back to the previous surviving generation."""
        with self.hang_guard("checkpoint"):
            return self._save_checkpoint_guarded(root, cursor)

    def _save_checkpoint_guarded(self, root: str, cursor: Any) -> str:
        import jax

        k = self._ckpt_seq
        self._ckpt_seq += 1
        d = os.path.join(root, f"ckpt-{k}")
        os.makedirs(d, exist_ok=True)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        fleet_digests: Dict[str, str] = {}
        for net_id in sorted(self.pipelines):
            p = self.pipelines[net_id]
            if p._gather_state_jit is None:
                specs = jax.tree_util.tree_map(lambda _: rep, p.trainer.state)
                p._gather_state_jit = self._shared_jit(
                    p, "gather_state",
                    lambda: jax.jit(lambda s: s, out_shardings=specs),
                )
            # the jitted gather is COLLECTIVE (every process dispatches
            # it), but only process 0 pays the host fetch + write — the
            # other processes' replicated copies never leave the device
            gathered = p._gather_state_jit(p.trainer.state)
            if self.pid == 0:
                leaves = [
                    self._fetch_replicated(l)
                    for l in jax.tree_util.tree_leaves(gathered)
                ]
                fleet_digests[f"fleet_{net_id}.npz"] = _atomic_savez(
                    os.path.join(d, f"fleet_{net_id}.npz"),
                    {f"leaf_{i}": l for i, l in enumerate(leaves)},
                )
        arrays: Dict[str, np.ndarray] = {}
        meta: Dict[str, Any] = {
            "cursor": cursor,
            "pipelines": {},
            # already-served outputs survive a restore: the request-topic
            # offsets are checkpointed past an answered Query, so the
            # response (and a deleted pipeline's predictions) would
            # otherwise vanish from the final output files
            "orphan_predictions": [
                [int(n), float(v)] for n, v in self.orphan_predictions
            ],
        }
        if self.pid == 0:
            meta["responses"] = [r.to_dict() for r in self.responses]
        for net_id in sorted(self.pipelines):
            p = self.pipelines[net_id]
            width = p.max_nnz if p.sparse else p.dim
            xdt = np.int32 if p.sparse else np.float32
            pend_x = (
                np.concatenate(p.pend_x)
                if p.pend_x else np.zeros((0, width), xdt)
            )
            pend_y = (
                np.concatenate(p.pend_y)
                if p.pend_y else np.zeros((0,), np.float32)
            )
            fore_x = (
                np.concatenate(p.fore_x)
                if p.fore_x else np.zeros((0, width), xdt)
            )
            arrays[f"n{net_id}_pend_x"] = pend_x
            arrays[f"n{net_id}_pend_y"] = pend_y
            arrays[f"n{net_id}_fore_x"] = fore_x
            if p.sparse:
                arrays[f"n{net_id}_pend_v"] = (
                    np.concatenate(p.pend_v)
                    if p.pend_v else np.zeros((0, width), np.float32)
                )
                arrays[f"n{net_id}_fore_v"] = (
                    np.concatenate(p.fore_v)
                    if p.fore_v else np.zeros((0, width), np.float32)
                )
                if len(p.test_set):
                    ti, tv, ty = p.test_set.arrays()
                else:
                    ti = np.zeros((0, width), np.int32)
                    tv = np.zeros((0, width), np.float32)
                    ty = np.zeros((0,), np.float32)
                arrays[f"n{net_id}_test_x"] = np.asarray(ti, np.int32)
                arrays[f"n{net_id}_test_v"] = np.asarray(tv, np.float32)
                arrays[f"n{net_id}_test_y"] = np.asarray(ty, np.float32)
            else:
                tx, ty = (
                    p.test_set.arrays() if len(p.test_set)
                    else (np.zeros((0, p.dim), np.float32),
                          np.zeros((0,), np.float32))
                )
                arrays[f"n{net_id}_test_x"] = np.asarray(tx, np.float32)
                arrays[f"n{net_id}_test_y"] = np.asarray(ty, np.float32)
            meta["pipelines"][str(net_id)] = {
                "holdout_count": p.holdout_count,
                "fitted": p.trainer.fitted,
                "steps_host": p.trainer._steps_host,
                "requeued": getattr(p.trainer, "requeued_rows", 0),
                "steps_run": p.steps_run,
                "predictions": p.predictions,
                "curve": p.curve,
                "global_rows": p.global_rows,
            }
        # the shard digest rides in the shard's OWN meta (each process
        # writes only its own files; the manifest carries proc 0's)
        meta["sha256"] = _atomic_savez(
            os.path.join(d, f"proc{self.pid}.npz"), arrays
        )
        _atomic_write_json(os.path.join(d, f"proc{self.pid}.json"), meta)
        if self.pid == 0:
            _atomic_write_json(
                os.path.join(d, "manifest.json"),
                {
                    "seq": k,
                    "processes": self.nproc,
                    "dp_global": self.dp_global,
                    "request_lines": [
                        self.pipelines[i].raw_line
                        for i in sorted(self.pipelines)
                    ],
                    # per-file integrity digests (restore verifies before
                    # trusting the generation; proc shards carry theirs
                    # in their own cursor metas)
                    "digests": fleet_digests,
                },
            )
        self.barrier()  # every process's files durable before the flip
        if self.pid == 0:
            _atomic_write_bytes(
                os.path.join(root, "LATEST"), f"ckpt-{k}".encode()
            )
            # retention: prune superseded snapshots (same policy as the
            # single-process CheckpointManager's keep/prune,
            # checkpoint/checkpoint.py) — only LATEST is ever restored,
            # a couple of spares survive a torn write of the newest
            keep = max(getattr(self.config, "checkpoint_keep", 3), 1)
            import shutil

            for name in os.listdir(root):
                if not name.startswith("ckpt-"):
                    continue
                try:
                    seq = int(name.split("-", 1)[1])
                except ValueError:
                    continue
                if seq <= k - keep:
                    shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        self.barrier()  # nobody races ahead of the visible pointer
        return d

    def _checkpoint_candidates(self, root: str) -> List[Tuple[int, str]]:
        """(seq, dir) of every snapshot under ``root``, newest first."""
        try:
            names = os.listdir(root)
        except OSError:
            return []
        out = []
        for name in names:
            if not name.startswith("ckpt-"):
                continue
            try:
                out.append(
                    (int(name.split("-", 1)[1]), os.path.join(root, name))
                )
            except ValueError:
                continue
        return sorted(out, reverse=True)

    def _validate_checkpoint(self, d: str) -> Optional[dict]:
        """Fully load-check every file THIS process needs from snapshot
        ``d`` (manifest, the proc shard pairs the rescale shard map hands
        it, every process's cursor meta, the fleet files); returns the
        manifest, or None — with the reason logged — when any file is
        missing, truncated, or undecodable. Loading every array is
        deliberate: a torn npz can open fine and fail only when its
        members decompress, and restore must never half-load. A snapshot
        from a DIFFERENT process count validates the shards this process
        will merge (``rescale_shard_map``) — unless rescale-restore is
        disabled, in which case only the manifest is checked (restore
        refuses with the actionable knob before touching any shard)."""
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                manifest = json.load(f)
            net_ids = [
                int(json.loads(line)["id"])
                for line in manifest["request_lines"]
            ]
            old_n = int(manifest.get("processes", self.nproc))
            if old_n != self.nproc and not self.rescale_restore:
                return manifest
            # cursor metas of EVERY old process (the Kafka offset union
            # needs them all; cheap JSON reads) — each carries its own
            # shard's sha256
            shard_digests: Dict[str, Any] = {}
            for q in range(old_n):
                with open(os.path.join(d, f"proc{q}.json")) as f:
                    shard_digests[f"proc{q}.npz"] = json.load(f).get(
                        "sha256"
                    )
            digests = dict(manifest.get("digests") or {})
            digests.update(shard_digests)
            paths = [
                os.path.join(d, f"proc{q}.npz")
                for q in rescale_shard_map(old_n, self.nproc, self.pid)
            ] + [
                os.path.join(d, f"fleet_{net_id}.npz") for net_id in net_ids
            ]
            for path in paths:
                # integrity first: a recorded digest must match the bytes
                # on disk EXACTLY (catches corruptions np.load would
                # happily half-decode); snapshots from before the digest
                # era (no recorded digest) fall through to the load check
                recorded = digests.get(os.path.basename(path))
                if recorded and _file_sha256(path) != recorded:
                    raise ValueError(
                        f"sha256 mismatch on {os.path.basename(path)}"
                    )
                with np.load(path) as z:
                    for key in z.files:
                        _ = z[key]
            return manifest
        except Exception as exc:
            self._warn(
                f"snapshot {os.path.basename(d)} failed validation: "
                f"{type(exc).__name__}: {exc}"
            )
            from omldm_tpu.runtime.events import RESTORE

            # reason-coded restore decision: this generation is untrusted
            # and the fleet will fall back to the previous surviving one
            self._record_event(
                RESTORE, "candidate_rejected",
                snapshot=os.path.basename(d),
                error=f"{type(exc).__name__}: {exc}",
            )
            return None

    def _agree_restore_target(
        self, root: str
    ) -> Tuple[Optional[str], Optional[dict]]:
        """Pick the newest snapshot EVERY process can fully load. Each
        process validates candidates newest-first; the fleet agrees on the
        min of the per-process bests, re-validating until one snapshot is
        good everywhere — a corrupt/truncated/withheld shard on any
        process falls the whole fleet back to the previous complete
        snapshot instead of crashing or half-loading (the role of Flink
        discarding an incomplete checkpoint and restoring the last
        COMPLETED one)."""
        ceiling: Optional[int] = None
        while True:
            local_seq, local_manifest = -1, None
            for seq, d in self._checkpoint_candidates(root):
                if ceiling is not None and seq > ceiling:
                    continue
                manifest = self._validate_checkpoint(d)
                if manifest is not None:
                    local_seq, local_manifest = seq, manifest
                    break
            # fleet minimum of the per-process newest-valid seq
            agreed = int(round(
                -self._collective_reduce([-float(local_seq)], "max")[0]
            ))
            if agreed < 0:
                return None, None
            if agreed != local_seq:
                local_manifest = self._validate_checkpoint(
                    os.path.join(root, f"ckpt-{agreed}")
                )
            ok = 1.0 if local_manifest is not None else 0.0
            all_ok = -self._collective_reduce([-ok], "max")[0]
            if all_ok > 0.5:
                return os.path.join(root, f"ckpt-{agreed}"), local_manifest
            ceiling = agreed - 1

    def restore_checkpoint(self, root: str) -> Optional[Any]:
        """Resume every process from the latest CONSISTENT snapshot;
        returns this process's saved cursor (None when no usable snapshot
        exists). Must be called before any data is consumed, by every
        process (the fleet-state placement — and the agreement on which
        snapshot is loadable everywhere — is collective). A snapshot with
        a corrupt/truncated/missing shard is skipped in favor of the
        previous complete one; the LATEST pointer is repointed and the
        unusable snapshots pruned so later incarnations never retry
        them."""
        with self.hang_guard("restore"):
            return self._restore_checkpoint_guarded(root)

    def _restore_checkpoint_guarded(self, root: str) -> Optional[Any]:
        import jax

        latest = os.path.join(root, "LATEST")
        if not os.path.exists(latest) and not self._checkpoint_candidates(
            root
        ):
            return None
        d, manifest = self._agree_restore_target(root)
        if d is None:
            self._warn(
                "no usable distributed snapshot (every candidate failed "
                "validation on some process); starting fresh"
            )
            from omldm_tpu.runtime.events import RESTORE

            self._record_event(RESTORE, "no_usable_snapshot")
            return None
        pointed = d
        if os.path.exists(latest):
            with open(latest, "rb") as f:
                pointed = os.path.join(root, f.read().decode().strip())
        if os.path.abspath(pointed) != os.path.abspath(d):
            self._warn(
                f"falling back from {os.path.basename(pointed)} to "
                f"{os.path.basename(d)} (newer snapshot incomplete)"
            )
            if self.pid == 0:
                # repoint + prune: the unusable snapshots must not be
                # retried by a later incarnation, and the next save reuses
                # their seq numbers
                import shutil

                chosen_seq = int(os.path.basename(d).split("-", 1)[1])
                for seq, cand in self._checkpoint_candidates(root):
                    if seq > chosen_seq:
                        shutil.rmtree(cand, ignore_errors=True)
                _atomic_write_bytes(
                    latest, os.path.basename(d).encode()
                )
            self.barrier()  # nobody proceeds past a half-pruned root
        old_n = int(manifest["processes"])
        if old_n != self.nproc:
            if not self.rescale_restore:
                # reason-coded refusal, not a fleet crash: the operator
                # pinned the strict count contract, so degrade to the
                # fresh-start path (the caller redeploys the requests
                # file) and name the knob that re-enables elasticity
                self._warn(
                    f"snapshot {os.path.basename(d)} was taken with "
                    f"{old_n} processes but this fleet has {self.nproc}, "
                    "and rescale-restore is disabled (--rescaleRestore "
                    "false) — starting fresh. Relaunch with "
                    "--rescaleRestore true (the default) to redistribute "
                    "the snapshot across the new process count."
                )
                from omldm_tpu.runtime.events import RESTORE

                self._record_event(
                    RESTORE, "rescale_restore_disabled",
                    snapshot_procs=old_n, fleet_procs=self.nproc,
                )
                return None
            if not self._rescale_count_pinned:
                self.rescales_performed += 1
            self._warn(
                f"rescale-restore: redistributing a {old_n}-process "
                f"snapshot across {self.nproc} processes "
                f"(fleet rows {int(manifest['dp_global'])} -> "
                f"{self.dp_global}; source stripe re-agreed)"
            )
            from omldm_tpu.runtime.events import RESTORE

            self._record_event(
                RESTORE, "rescale_redistribution",
                snapshot_procs=old_n, fleet_procs=self.nproc,
                snapshot=os.path.basename(d),
            )
        if old_n == self.nproc and self.events is not None:
            from omldm_tpu.runtime.events import RESTORE

            self._record_event(
                RESTORE, "snapshot", snapshot=os.path.basename(d),
            )
        self._ckpt_seq = int(manifest["seq"]) + 1
        # redeploy the pipeline map from the recorded request lines (no
        # broadcast needed: every process reads the same manifest). A live
        # pipeline whose latest request was an Update redeploys as a Create
        # — the gatekeeper would reject an Update for a pipeline that does
        # not exist yet in this incarnation.
        import dataclasses as _dc

        for i, line in enumerate(manifest["request_lines"]):
            self._deploy_beat(i)
            request = Request.from_json(line)
            assert request is not None, "corrupt manifest request line"
            if request.request == RequestType.UPDATE:
                request = _dc.replace(request, request=RequestType.CREATE)
            self._deploy(request, line)
        from jax.sharding import PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        # shards this process merges (exactly [pid] when the count is
        # unchanged; the retiring shards' union on shrink; empty for a
        # grow-seeded new process) + every process's cursor meta (the
        # Kafka offset union needs them all)
        shards = rescale_shard_map(old_n, self.nproc, self.pid)
        all_metas: List[dict] = []
        for q in range(old_n):
            with open(os.path.join(d, f"proc{q}.json")) as f:
                all_metas.append(json.load(f))
        metas = [all_metas[q] for q in shards]
        self.orphan_predictions = [
            (int(n), float(v))
            for m in metas
            for n, v in m.get("orphan_predictions", [])
        ]
        if self.pid == 0:
            # responses live on old process 0's meta; shard 0 always maps
            # to new process 0 (0 % M == 0)
            self.responses.extend(
                QueryResponse.from_dict(r)
                for r in all_metas[0].get("responses", [])
            )
        shard_arrays = [
            np.load(os.path.join(d, f"proc{q}.npz")) for q in shards
        ]
        lo = self.pid * self.dp_local
        for net_id in sorted(self.pipelines):
            p = self.pipelines[net_id]
            fleet = np.load(os.path.join(d, f"fleet_{net_id}.npz"))
            # leaf index -> top-level state key (params/preps/ef/...) so
            # the rescale redistribution can apply per-leaf merge rules;
            # tree_flatten_with_path walks the same order tree_leaves
            # walked at save time
            paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(
                p.trainer.state
            )
            placed = []
            for i, (path, _) in enumerate(paths_leaves):
                key = str(getattr(path[0], "key", path[0]))
                full = _rescale_fleet_leaf(
                    fleet[f"leaf_{i}"], key, self.dp_global
                )
                local = full[lo : lo + self.dp_local]
                placed.append(
                    host_local_array(local, self.mesh, P("dp", "hub"))
                )
            p.trainer.state = jax.tree_util.tree_unflatten(treedef, placed)
            pms = [m["pipelines"][str(net_id)] for m in metas]
            # additive per-partition counters SUM across merged shards;
            # lockstep counters (collective step counts) are identical on
            # every process at a synchronized cut, so max == any
            p.holdout_count = sum(int(pm["holdout_count"]) for pm in pms)
            p.trainer._fitted_host = sum(int(pm["fitted"]) for pm in pms)
            p.trainer._steps_host = max(
                (int(pm["steps_host"]) for pm in pms), default=0
            )
            p.trainer.requeued_rows = sum(int(pm["requeued"]) for pm in pms)
            p.steps_run = max((int(pm["steps_run"]) for pm in pms), default=0)
            p.predictions = [float(v) for pm in pms for v in pm["predictions"]]
            # the learning curve is fleet-global (collectively reduced at
            # save time): the first merged shard speaks for everyone, and
            # a grow-seeded process adopts old process 0's copy
            curve_src = pms[0] if pms else all_metas[0]["pipelines"].get(
                str(net_id), {"curve": [], "global_rows": 0}
            )
            p.curve = [(float(l), int(r)) for l, r in curve_src["curve"]]
            p.global_rows = int(curve_src["global_rows"])
            if shard_arrays:
                self._restore_buffers(p, net_id, shard_arrays)
        return _merge_cursors([m["cursor"] for m in all_metas])

    def _restore_buffers(
        self, p: _DistPipeline, net_id: int, shard_arrays: List[Any]
    ) -> None:
        """Merge the staged pending/forecast/holdout buffers of every
        checkpoint shard this process owns (one shard on a same-count
        restore; the retiring stripes' union on shrink — rows interleave
        round-robin so the merged buffers stay a fair stream-order mix,
        the in-process absorb's holdout-interleave semantics)."""
        pend = [a[f"n{net_id}_pend_x"] for a in shard_arrays]
        if sum(b.shape[0] for b in pend):
            perm = _interleave_perm([b.shape[0] for b in pend])
            p.pend_x = [np.concatenate(pend)[perm]]
            if p.sparse:
                p.pend_v = [
                    np.concatenate(
                        [a[f"n{net_id}_pend_v"] for a in shard_arrays]
                    )[perm]
                ]
            p.pend_y = [
                np.concatenate(
                    [a[f"n{net_id}_pend_y"] for a in shard_arrays]
                )[perm]
            ]
            p.pend_n = int(p.pend_x[0].shape[0])
        fore = [a[f"n{net_id}_fore_x"] for a in shard_arrays]
        if sum(b.shape[0] for b in fore):
            perm = _interleave_perm([b.shape[0] for b in fore])
            p.fore_x = [np.concatenate(fore)[perm]]
            if p.sparse:
                p.fore_v = [
                    np.concatenate(
                        [a[f"n{net_id}_fore_v"] for a in shard_arrays]
                    )[perm]
                ]
            p.fore_n = int(p.fore_x[0].shape[0])
        test = [a[f"n{net_id}_test_x"] for a in shard_arrays]
        if sum(b.shape[0] for b in test):
            perm = _interleave_perm([b.shape[0] for b in test])
            tx = np.concatenate(test)[perm]
            ty = np.concatenate(
                [a[f"n{net_id}_test_y"] for a in shard_arrays]
            )[perm]
            # merged holdouts can overflow the ring (shrink folds several
            # full rings into one): evicted rows RE-FEED the training
            # buffer, exactly what the live holdout split does with its
            # evictions (_buffer_rows) — rows conserve across a rescale,
            # none vanish with the retired partitions
            if p.sparse:
                tv = np.concatenate(
                    [a[f"n{net_id}_test_v"] for a in shard_arrays]
                )[perm]
                ev_i, ev_v, ev_y, ev_src = p.test_set.append_many(tx, tv, ty)
                if ev_src.size:
                    p.pend_x.append(np.asarray(ev_i, np.int32))
                    p.pend_v.append(np.asarray(ev_v, np.float32))
                    p.pend_y.append(np.asarray(ev_y, np.float32))
                    p.pend_n += int(ev_src.size)
            else:
                ev_x, ev_y, ev_src = p.test_set.append_many(tx, ty)
                if ev_src.size:
                    p.pend_x.append(np.asarray(ev_x, np.float32))
                    p.pend_y.append(np.asarray(ev_y, np.float32))
                    p.pend_n += int(ev_src.size)


# --- drive loops -----------------------------------------------------------


def _manifest_is_sparse(flags: Dict[str, str]) -> bool:
    """Restores skip the requests file, so the drive-mode choice sniffs
    the snapshot manifests' recorded Create lines. Sparsity is a
    job-level property (the stream mode is pinned by the first deploy and
    recorded in every snapshot), so when the newest manifest is
    unreadable — the corrupt-snapshot case restore itself falls back
    from — ANY readable candidate answers the question."""
    root = flags.get("checkpointDir")
    if not root:
        return False
    candidates = []
    latest = os.path.join(root, "LATEST")
    if os.path.exists(latest):
        with open(latest, "rb") as f:
            candidates.append(os.path.join(root, f.read().decode().strip()))
    try:
        names = [
            n for n in os.listdir(root)
            if n.startswith("ckpt-") and n.split("-", 1)[1].isdigit()
        ]
    except OSError:
        names = []
    names.sort(key=lambda n: -int(n.split("-", 1)[1]))
    candidates += [os.path.join(root, n) for n in names]
    for d in candidates:
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            continue  # unreadable: restore falls back the same way
        for line in manifest.get("request_lines", []):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            ds = (obj.get("learner") or {}).get("dataStructure") or {}
            if ds.get("sparse"):
                return True
        return False  # first READABLE manifest decides
    return False


def _flag_true(flags: Dict[str, str], key: str) -> bool:
    return flags.get(key, "").lower() in ("true", "1", "yes")


def _heartbeat(flags: Dict[str, str], pid: int, frame=0) -> bool:
    """Touch this process's heartbeat file (the supervisor's liveness
    channel). Called at every synchronized pump point, so a process wedged
    in a collective (peer died) stops beating and gets detected. The file
    body is the compact metrics frame
    ``<epoch> <pressure-level> [key=value ...]`` — token 2 is the
    window-peak overload level and the key=value tail carries the
    host-plane signals (``serveP99``/``imbalance``/``backlog``) the
    autoscaling supervisor folds across the fleet
    (supervisor._beat_frame; a bare int ``frame`` writes the legacy
    two-token form). Absent/zero when the overload plane is unarmed.
    Returns False when the disk refused the write (ENOSPC survival: the
    caller counts the dropped beat, the worker keeps running)."""
    d = flags.get("heartbeatDir")
    if not d:
        return True
    if isinstance(frame, dict):
        level = int(frame.get("level", 0))
        tail = "".join(
            f" {k}={frame[k]}"
            for k in ("serveP99", "imbalance", "backlog", "events",
                      "alerts")
            if k in frame
        )
    else:
        level, tail = int(frame), ""
    try:
        os.makedirs(d, exist_ok=True)
        # atomic replace: the supervisor polls this file between writes,
        # and a torn read of a truncate-in-progress beat would feed the
        # autoscaler a phantom level-0 sample mid-burst
        path = os.path.join(d, f"proc{pid}.hb")
        with open(path + ".tmp", "w") as f:
            f.write(f"{time.time()} {level}{tail}")
        os.replace(path + ".tmp", path)
        return True
    except OSError:
        return False  # a full/odd disk must not kill the job over telemetry


def _maybe_rescale_exit(
    job: DistributedStreamJob, flags: Dict[str, str], cursor: Any
) -> None:
    """Honor a standing rescale signal from the autoscaling supervisor:
    process 0 reads the target process count from the signal file, the
    fleet AGREES on it over the fabric (file visibility can race between
    processes — an unagreed exit would wedge the survivors in their next
    collective), snapshots the consistent cut, and every process exits
    with the rescale code so the supervisor relaunches at the new count
    with ``--restore``. No signal dir armed (the default) => zero cost,
    no extra collectives."""
    sig_dir = flags.get("rescaleSignalDir")
    if not sig_dir:
        return
    target = 0
    if job.pid == 0:
        try:
            with open(os.path.join(sig_dir, "RESCALE")) as f:
                target = int(f.read().strip() or 0)
        except (OSError, ValueError):
            target = 0
    agreed = int(job._collective_reduce([float(target)], "max")[0])
    if agreed <= 0 or agreed == job.nproc:
        return
    root = flags.get("checkpointDir")
    if not root:
        # without a checkpoint dir the relaunch would lose all state;
        # refuse loudly (the supervisor refuses to arm autoscale without
        # one, so this is a manually-miswired fleet)
        job._warn(
            "rescale signal ignored: no --checkpointDir to carry state "
            "across the relaunch"
        )
        return
    d = job.save_checkpoint(root, cursor)
    job._warn(
        f"rescale signal honored: snapshot {os.path.basename(d)} taken, "
        f"fleet exiting to relaunch at {agreed} processes"
    )
    if job.events is not None:
        from omldm_tpu.runtime.events import RESCALE

        job.events.record(
            RESCALE, "supervisor_signal_agreed",
            from_procs=job.nproc, to_procs=agreed,
            snapshot=os.path.basename(d),
        )
        # the pre-rescale ring must survive the process exit: this dump
        # is what the supervisor's incident bundle reads
        job.events.incident("rescale")
    from omldm_tpu.runtime.supervisor import RESCALE_EXIT

    raise SystemExit(RESCALE_EXIT)


def _make_injector(job: DistributedStreamJob, flags: Dict[str, str]):
    from omldm_tpu.runtime.supervisor import DistributedFaultInjector

    injector = DistributedFaultInjector(flags, job.pid)
    # launch-refusal fault: fires HERE, before this process's first
    # heartbeat, so the supervisor's classifier sees a worker that died
    # without ever coming up (the LAUNCH class)
    injector.on_launch()
    return injector


def _sync_requests_from_flags(
    job: DistributedStreamJob, flags: Dict[str, str]
) -> None:
    """Deploy the --requests file (process 0 reads, everyone syncs)."""
    lines: List[str] = []
    if job.pid == 0 and flags.get("requests"):
        with open(flags["requests"]) as f:
            lines = [l.strip() for l in f if l.strip()]
    job.sync_requests(lines)


def _load_request_schedule(
    flags: Dict[str, str]
) -> List[Tuple[int, str]]:
    """The count-clocked mid-stream request schedule (--requestSchedule):
    JSONL ``{"atRecord": N, "request": {...}}`` entries, sorted by
    position. EVERY process reads the shared file and computes dueness
    locally from the cursor (identical across processes), so the
    collective sync fires only at pump points where something is due —
    the deterministic, replayable stand-in for the Kafka requests topic's
    wall-clock polling."""
    path = flags.get("requestSchedule")
    if not path:
        return []
    entries: List[Tuple[int, str]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            entries.append(
                (int(obj["atRecord"]), json.dumps(obj["request"]))
            )
    entries.sort(key=lambda e: e[0])
    return entries


def _schedule_start(
    schedule: List[Tuple[int, str]], resume_cursor: int
) -> int:
    """First schedule index NOT yet delivered at ``resume_cursor``:
    entries at/before the checkpoint cut were applied pre-snapshot and
    live in the restored manifest — redelivering them would double-churn
    the topology."""
    i = 0
    while i < len(schedule) and schedule[i][0] <= resume_cursor:
        i += 1
    return i


def _deliver_due_requests(
    job: DistributedStreamJob,
    schedule: List[Tuple[int, str]],
    idx: int,
    cursor: int,
) -> int:
    """Deliver every schedule entry with ``atRecord <= cursor`` (one
    collective sync for the batch); returns the advanced index. Called at
    the synchronized pump point BEFORE the checkpoint cadence, so a
    snapshot at this cut already contains the new topology."""
    if idx >= len(schedule) or schedule[idx][0] > cursor:
        return idx
    due: List[str] = []
    while idx < len(schedule) and schedule[idx][0] <= cursor:
        due.append(schedule[idx][1])
        idx += 1
    job.sync_requests(due if job.pid == 0 else [])
    return idx


def _restore_or_fresh(job: DistributedStreamJob, flags: Dict[str, str]):
    """Restore the latest consistent snapshot; when NO candidate is usable
    (all corrupt/withheld — restore_checkpoint already warned), degrade to
    a fresh run by redeploying the requests file instead of dying with no
    pipelines — Flink's behavior for a job restarted without a completed
    checkpoint. Returns the restored cursor or None."""
    cur = job.restore_checkpoint(flags["checkpointDir"])
    if cur is None and not job.pipelines:
        _sync_requests_from_flags(job, flags)
    return cur


def _chunk_tick(
    job: DistributedStreamJob, flags: Dict[str, str],
    chunk_idx: int, cursor: Any, injector, records: int = 0,
) -> None:
    """One synchronized pump point: heartbeat, checkpoint cadence, fault
    injection. Every process evaluates the same checkpoint condition at
    the same chunk index, so snapshots are collective-consistent; injected
    crashes fire here too, so a kill lands at one well-defined cut (the
    supervisor then relaunches the fleet with --restore, Flink's
    global-restart strategy)."""
    if not _heartbeat(flags, job.pid, job.heartbeat_frame()):
        # dropped-write counter, not a dead worker (ENOSPC survival);
        # surfaces as blackboxWriteErrors in the job report
        job.hb_write_errors += 1
    job.note_event_records(records)
    if job.events is not None and job.events.dirty:
        # dump-on-dirty: decision events are rare on this engine, so the
        # atomic ring rewrite is rare too — and a worker killed between
        # ticks leaves a near-current black box for the bundle
        job.events.dump()
    every = int(flags.get("checkpointEvery", "0"))
    root = flags.get("checkpointDir")
    if every > 0 and root and (chunk_idx + 1) % every == 0:
        d = job.save_checkpoint(root, cursor)
        injector.on_checkpoint(d)
    injector.note_records(records)
    injector.on_chunk(chunk_idx)
    # autoscaling: a supervisor-issued rescale signal checkpoints this
    # consistent cut and exits the fleet for a relaunch at the new count
    _maybe_rescale_exit(job, flags, cursor)


def _sparse_tools(job: DistributedStreamJob):
    """(SparseFastParser, SparseVectorizer) for the job's pinned COO
    layout — shared by the file and Kafka sparse drives."""
    from omldm_tpu.ops.native import SparseFastParser
    from omldm_tpu.runtime.vectorizer import SparseVectorizer

    p0 = next(iter(job.pipelines.values()))
    dense_budget = job.dim - job.sparse_hash_space
    parser = SparseFastParser(
        dense_budget, job.sparse_hash_space, p0.max_nnz
    )
    vec = SparseVectorizer(job.dim, job.sparse_hash_space, p0.max_nnz)
    return parser, vec


def _consume_sparse_block(
    job: DistributedStreamJob, parser, vec, block: bytes,
    line_base: int, nproc: int, pid: int, force_forecast: bool = False,
) -> int:
    """Parse a line-aligned COO block, keep this process's stride (row
    line_base+i belongs to process (line_base+i) % nproc — pass nproc=1
    for Kafka mode, where partition assignment already partitioned the
    stream), and buffer train/forecast rows for every pipeline. Rows the
    C parser defers (valid == 2: escaped categoricals, odd shapes) route
    through the Python codec at their stream position. Returns the number
    of lines consumed."""
    from omldm_tpu.api.data import FORECASTING, DataInstance
    from omldm_tpu.runtime.vectorizer import F32_MAX

    idx, val, y, op, valid = parser.parse(block)
    n = idx.shape[0]
    if n == 0:
        return 0
    gidx = line_base + np.arange(n)
    mine = (gidx % nproc) == pid
    fast = mine & (valid == 1)
    if force_forecast:
        fore = fast
        train = np.zeros_like(fast)
    else:
        train = fast & (op == 0)
        fore = fast & (op != 0)
    # specials interleave with fast rows in stream order (same contract
    # as the single-process COO bridge): split the block at fallback rows
    fb = np.nonzero(mine & (valid == 2))[0]
    if not fb.size:
        if train.any():
            job.handle_partition_rows_sparse(idx[train], val[train], y[train])
        if fore.any():
            job.handle_forecast_rows_sparse(idx[fore], val[fore])
        return n
    lines = block.split(b"\n")
    prev = 0
    for s in list(fb) + [n]:
        s = int(s)
        seg = slice(prev, s)
        seg_train = train[seg]
        seg_fore = fore[seg]
        if seg_train.any():
            job.handle_partition_rows_sparse(
                idx[seg][seg_train], val[seg][seg_train], y[seg][seg_train]
            )
        if seg_fore.any():
            job.handle_forecast_rows_sparse(
                idx[seg][seg_fore], val[seg][seg_fore]
            )
        if s >= n:
            break
        inst = DataInstance.from_json(
            lines[s].decode("utf-8", errors="replace")
        )
        if inst is not None:
            i1, v1 = vec.vectorize(inst)
            if force_forecast or inst.operation == FORECASTING:
                job.handle_forecast_rows_sparse(i1[None], v1[None])
            else:
                yv = (
                    0.0 if inst.target is None
                    else float(min(max(float(inst.target), -F32_MAX), F32_MAX))
                )
                job.handle_partition_rows_sparse(
                    i1[None], v1[None], np.asarray([yv], np.float32)
                )
        prev = s + 1
    return n


def _drive_file_sparse(job: DistributedStreamJob, flags: Dict[str, str]) -> None:
    """Sparse (padded-COO) file drive: line-aligned chunks through the C
    COO parser, row i striped to process i % nproc — the sparse twin of
    the dense strided drive. Checkpoint cursors record the line-aligned
    BYTE offset plus the global line count (both needed: bytes to seek,
    lines to keep the stripe phase)."""
    from omldm_tpu.runtime.spmd_bridge import _line_aligned_chunks

    resume = {"bytes": 0, "lines": 0}
    if _flag_true(flags, "restore") and flags.get("checkpointDir"):
        cur = _restore_or_fresh(job, flags)
        if cur is not None:
            resume = dict(cur)
            job._warn(f"restored; resuming at {resume}")
    assert job.dim is not None, "no pipeline deployed and no snapshot found"
    injector = _make_injector(job, flags)
    parser, vec = _sparse_tools(job)
    chunk_rows = int(flags.get("chunkRows", str(CHUNK_ROWS)))
    # size chunks in bytes from a crude per-line estimate; pump cadence
    # only needs to be IDENTICAL across processes, which byte-chunking is
    chunk_bytes = max(chunk_rows * 256, 1 << 16)
    consumed = int(resume["bytes"])
    line_base = int(resume["lines"])
    chunk_idx = 0
    for buf, stop in _line_aligned_chunks(
        flags["trainingData"], chunk_bytes, start_offset=consumed
    ):
        block = bytes(memoryview(buf)[:stop])
        n = _consume_sparse_block(
            job, parser, vec, block, line_base, job.nproc, job.pid
        )
        line_base += n
        consumed += stop
        job.pump()
        _chunk_tick(
            job, flags, chunk_idx,
            {"bytes": consumed, "lines": line_base},
            injector, records=n,
        )
        chunk_idx += 1
    job.flush()


def _drive_file(job: DistributedStreamJob, flags: Dict[str, str]) -> None:
    """Strided partition of a shared JSON-lines file: row i belongs to
    process i % nproc (the deterministic stand-in for a Kafka partition
    assignment; the whole-file read models the shared offsets). Uses the
    same fused C ingest parser as the single-process CLI."""
    from omldm_tpu.runtime.fast_ingest import iter_file_batches

    resume_cursor = 0
    if _flag_true(flags, "restore") and flags.get("checkpointDir"):
        cur = _restore_or_fresh(job, flags)
        if cur is not None:
            resume_cursor = int(cur)
            job._warn(f"restored; resuming at row {resume_cursor}")
    assert job.dim is not None, "no pipeline deployed and no snapshot found"
    injector = _make_injector(job, flags)
    schedule = _load_request_schedule(flags)
    sched_idx = _schedule_start(schedule, resume_cursor)
    cursor = 0
    chunk_idx = 0
    chunk_rows = int(flags.get("chunkRows", str(CHUNK_ROWS)))
    for bx, by, bop in iter_file_batches(
        flags["trainingData"], job.dim, chunk_rows, job.hash_dims
    ):
        n = bx.shape[0]
        if cursor + n <= resume_cursor:
            cursor += n
            continue
        if cursor < resume_cursor:
            skip = resume_cursor - cursor
            bx, by, bop = bx[skip:], by[skip:], bop[skip:]
            cursor = resume_cursor
            n = bx.shape[0]
        gidx = cursor + np.arange(n)
        mine = (gidx % job.nproc) == job.pid
        cursor += n
        train = mine & (bop == 0)
        if train.any():
            job.handle_partition_rows(bx[train], by[train])
        fore = mine & (bop != 0)
        if fore.any():
            job.handle_forecast_rows(bx[fore])
        # synchronized pump point: every process sees the same chunk
        # sequence. Scheduled requests land BEFORE the checkpoint cadence
        # so a snapshot at this cut carries the new topology (a restore
        # never redelivers them — _schedule_start skips the applied
        # prefix)
        job.pump()
        sched_idx = _deliver_due_requests(job, schedule, sched_idx, cursor)
        _chunk_tick(job, flags, chunk_idx, cursor, injector, records=n)
        chunk_idx += 1
    # entries scheduled past the end of the stream still belong to the
    # storm: deliver them at the final cut instead of dropping silently
    if sched_idx < len(schedule):
        sched_idx = _deliver_due_requests(
            job, schedule, sched_idx, schedule[-1][0]
        )
    job.flush()


def _tp_key(tp) -> str:
    return f"{tp.topic}:{tp.partition}"


def _drive_kafka(job: DistributedStreamJob, flags: Dict[str, str]) -> None:
    """Partitioned Kafka ingest: each process consumes an ASSIGNED set of
    partitions (partition index mod nproc — Flink's static per-subtask
    assignment, KafkaUtils.scala:11-31 / README.md:22-26, rather than
    broker-side group rebalance), tracks per-partition offsets for
    checkpointing, and pumps at synchronized poll windows. Mid-stream
    requests are polled from the requests topic by process 0 and broadcast
    over the fabric each window. Record values are parsed by the fused C
    ingest parser (PackedBatcher), one batcher per topic so forecast-topic
    records are forced to the forecast operation like the single-process
    sources."""
    try:
        from kafka import KafkaConsumer, TopicPartition
    except ImportError as e:
        raise ImportError(
            "Kafka ingest needs the 'kafka-python' package (or an injected "
            "compatible module); use --trainingData file replay otherwise."
        ) from e
    from omldm_tpu.runtime.fast_ingest import PackedBatcher

    brokers = flags["kafkaBrokers"]
    train_topic = flags.get("kafkaTrainTopic", "trainingData")
    fore_topic = flags.get("kafkaForecastTopic", "forecastingData")
    req_topic = flags.get("kafkaRequestTopic", "requests")
    poll_ms = int(flags.get("kafkaPollMs", "300"))

    offsets: Dict[str, int] = {}
    req_offsets: Dict[str, int] = {}
    if _flag_true(flags, "restore") and flags.get("checkpointDir"):
        cur = job.restore_checkpoint(flags["checkpointDir"])
        if cur is not None:
            offsets = dict(cur.get("data", {}))
            req_offsets = dict(cur.get("requests", {}))
            job._warn(f"restored; resuming at offsets {offsets}")

    injector = _make_injector(job, flags)
    consumer = KafkaConsumer(
        bootstrap_servers=brokers, consumer_timeout_ms=poll_ms
    )
    # broker chaos (--kafkaChaos flag / OMLDM_CHAOS_KAFKA env): seeded
    # drop/dup/reorder on the DATA record stream — dropped records'
    # offsets are never committed, so checkpoint/restore replays them:
    # at-least-once, exactly the reference's Kafka source contract. The
    # control (requests) consumer stays clean: duplicated Creates are
    # dropped by the admit gate anyway, but lost ones would change the
    # topology
    from omldm_tpu.runtime.supervisor import maybe_chaos_consumer

    consumer = maybe_chaos_consumer(consumer, flags, name=f"kafka-p{job.pid}")

    def _partitions(client, topic, retries=5):
        # metadata fetch through the shared backoff helper (no hand-rolled
        # sleep loops); [] after the budget keeps the degrade path
        import dataclasses as _dc

        from omldm_tpu.runtime.kafka_io import (
            CONNECT_RETRY,
            _partitions_with_retry,
        )

        policy = _dc.replace(CONNECT_RETRY, attempts=retries)
        return sorted(_partitions_with_retry(client, topic, policy) or [])

    def _seek_or_resume(client, tp, saved_offsets):
        """Seek to the snapshot offset, else to the LOG START — recording
        the broker-reported position (not a literal 0: a retention-trimmed
        partition starts later, and checkpointing 0 would make restore
        seek out of range and silently fall back to 'latest')."""
        saved = saved_offsets.get(_tp_key(tp))
        if saved is not None:
            client.seek(tp, saved)
            return
        # bounded experiment streams consume from the start (the
        # reference's runs pre-load partitioned topics, README.md:22-26)
        client.seek_to_beginning(tp)
        try:
            saved_offsets[_tp_key(tp)] = int(client.position(tp))
        except Exception:
            saved_offsets[_tp_key(tp)] = 0

    # partition -> process assignment: partition p of topic t belongs to
    # process p % nproc (Flink's static per-subtask assignment, PER TOPIC
    # so a topic discovered later never shifts an earlier topic's
    # striping). Process 0's metadata view is AUTHORITATIVE and travels
    # over the fabric: independently-retried partitions_for_topic views
    # can diverge on freshly-created topics, which would silently
    # double-assign or drop partitions if each process striped its own
    # list. Topics still absent (auto-created later — the supported
    # late-start pattern the startup idle bound waits through) are
    # re-probed every window until found, INDEPENDENTLY per topic.
    assigned: List[Any] = []
    undiscovered = [train_topic, fore_topic]
    # rotating stripe base: partition p of the i-th discovered partition
    # group goes to process (p + base) % nproc, base advancing by each
    # group's size — so single-partition topics SPREAD across processes
    # instead of all landing on process 0. Discovery events arrive in
    # broadcast order, so every process advances the base identically.
    stripe_base = [0]

    def _assign_partitions(retries: int) -> None:
        assign_payload: List[str] = []
        if job.pid == 0:
            found = {
                topic: _partitions(consumer, topic, retries)
                for topic in undiscovered
            }
            assign_payload = [json.dumps({"assign": found})]
        [assign_line] = job._broadcast_lines(assign_payload)
        found = json.loads(assign_line)["assign"]
        changed = False
        # iterate in the stable (train, fore) order, not dict order
        for topic in [t for t in (train_topic, fore_topic) if t in found]:
            parts = found[topic]
            if not parts:
                continue
            undiscovered.remove(topic)
            changed = True
            base = stripe_base[0]
            stripe_base[0] += len(parts)
            assigned.extend(
                TopicPartition(topic, p)
                for p in parts if (p + base) % job.nproc == job.pid
            )
        if changed and assigned:
            consumer.assign(assigned)
            for tp in assigned:
                _seek_or_resume(consumer, tp, offsets)

    _assign_partitions(retries=5)
    # process 0 owns the request topic (single-partition control stream);
    # its offsets are checkpointed too — replaying the whole topic on a
    # restore would re-run Updates (wiping the restored model) and
    # re-answer Queries. Like the data topics, a requests topic
    # auto-created after launch is re-probed each window.
    req_consumer = None
    req_assigned = [False]
    if job.pid == 0:
        req_consumer = KafkaConsumer(
            bootstrap_servers=brokers, consumer_timeout_ms=poll_ms
        )

    def _assign_requests(retries: int) -> None:
        # process-0-local (no collective): only it polls the topic
        if req_consumer is None or req_assigned[0]:
            return
        req_tps = [
            TopicPartition(req_topic, p)
            for p in _partitions(req_consumer, req_topic, retries)
        ]
        if req_tps:
            req_assigned[0] = True
            req_consumer.assign(req_tps)
            for tp in req_tps:
                _seek_or_resume(req_consumer, tp, req_offsets)

    _assign_requests(retries=5)

    chunk_rows = int(flags.get("chunkRows", str(CHUNK_ROWS)))
    # batchers are built once the stream width is known (the first Create
    # may arrive on the requests topic mid-run); until then data partitions
    # are simply not polled, so their offsets — and the records — wait in
    # the broker exactly as they would for a slow Flink subtask. A sparse
    # stream swaps in the COO parser (partition assignment already
    # partitioned the stream, so no row striding: nproc=1 in the helper).
    batchers: Dict[str, Any] = {}
    sparse_tools = [None]

    def _ensure_batchers():
        if not batchers and job.dim is not None:
            if job.stream_mode == "sparse":
                sparse_tools[0] = _sparse_tools(job)
                batchers[train_topic] = "sparse"
                batchers[fore_topic] = "sparse"
            else:
                batchers[train_topic] = PackedBatcher(
                    job.dim, chunk_rows, job.hash_dims
                )
                batchers[fore_topic] = PackedBatcher(
                    job.dim, chunk_rows, job.hash_dims
                )
        return bool(batchers)

    def _feed(topic, batches):
        for bx, by, bop in batches:
            if topic == fore_topic:
                job.handle_forecast_rows(bx)
            else:
                train = bop == 0
                if train.any():
                    job.handle_partition_rows(bx[train], by[train])
                if (~train).any():
                    job.handle_forecast_rows(bx[~train])

    def _feed_window(topic, wb):
        """One bulk parse per topic per poll window."""
        if batchers[topic] == "sparse":
            parser, vec = sparse_tools[0]
            _consume_sparse_block(
                job, parser, vec, bytes(wb), 0, 1, 0,
                force_forecast=(topic == fore_topic),
            )
        else:
            _feed(topic, batchers[topic].feed_buffer(wb, 0, len(wb)))

    chunk_idx = 0
    idle_windows = 0
    idle_limit = int(flags.get("idleWindows", "2"))
    startup_limit = int(flags.get("startupIdleWindows", "600"))
    # restores count as deployed: the manifest already rebuilt pipelines
    ever_deployed = bool(job.pipelines)
    # upstream backpressure (runtime/overload.py): while this process's
    # staging backlog is past backlogCritical its DATA partitions pause —
    # records wait in the broker (offsets uncommitted, replayable) while
    # pump() drains the backlog; the requests consumer never pauses (the
    # control plane must keep flowing). State is per process.
    data_paused = [False]
    overload_armed = job.overload_cfg is not None
    while True:
        # 1. control plane: new request lines, broadcast to everyone
        req_lines: List[str] = []
        if req_consumer is not None:
            _assign_requests(retries=1)
            while True:
                try:
                    rec = next(req_consumer)
                except StopIteration:
                    break
                req_offsets[_tp_key(rec)] = rec.offset + 1
                v = rec.value
                req_lines.append(
                    v.decode("utf-8", "replace") if isinstance(v, bytes) else v
                )
        job.sync_requests(req_lines)
        # 1b. late partition discovery: data topics auto-created after
        # launch get assigned once their metadata appears (single attempt
        # per window; the decision to re-try is broadcast-agreed, so every
        # process keeps issuing the same collectives)
        if undiscovered:
            _assign_partitions(retries=1)
            # a re-assign rebuilds the consumer's partition state and
            # silently DROPS any standing pause (kafka-python semantics)
            # — mark the valve open so the block below re-issues the
            # pause immediately while the level is still CRITICAL
            data_paused[0] = False
        # 1c. overload backpressure valve (pause/resume are best-effort:
        # test fakes without the kafka-python API just skip the pause and
        # rely on the chunk_rows poll bound)
        if overload_armed and assigned:
            level = job.overload_level()
            if level >= 2 and not data_paused[0]:
                pause = getattr(consumer, "pause", None)
                if pause is not None:
                    pause(*assigned)
                    data_paused[0] = True
                    job._warn(
                        f"overload CRITICAL (backlog {job.backlog_rows()} "
                        "rows): pausing data consumption"
                    )
                    from omldm_tpu.runtime.events import PAUSE

                    job._record_event(
                        PAUSE, "overload_critical",
                        backlog=job.backlog_rows(),
                    )
            elif level < 2 and data_paused[0]:
                resume = getattr(consumer, "resume", None)
                if resume is not None:
                    resume(*assigned)
                data_paused[0] = False
                job._warn("overload cleared: resuming data consumption")
                from omldm_tpu.runtime.events import PAUSE

                job._record_event(PAUSE, "overload_cleared")
        # 2. data: drain this window's records from the assigned
        # partitions. Record values are ACCUMULATED into one line buffer
        # per topic and parsed with a single bulk C call per topic per
        # window — per-record feed_buffer calls would pay a Python/ctypes
        # round trip per line and forfeit the block parser.
        had_rows = 0
        polled = 0
        win_bufs = {t: bytearray() for t in batchers} if _ensure_batchers() else {}
        while win_bufs and polled < chunk_rows:
            try:
                rec = next(consumer)
            except StopIteration:
                break
            polled += 1
            had_rows = 1
            offsets[_tp_key(rec)] = rec.offset + 1
            wb = win_bufs.get(rec.topic)
            if wb is None:
                continue
            v = rec.value
            wb += v if isinstance(v, bytes) else str(v).encode()
            if not wb.endswith(b"\n"):
                wb += b"\n"
        for topic, wb in win_bufs.items():
            if wb:
                _feed_window(topic, wb)
        for topic, b in batchers.items():
            if b == "sparse":
                continue  # the COO parser consumes whole windows, no tail
            tail = b.flush()
            if tail:
                _feed(topic, [tail])
        # 3. synchronized pump + checkpoint cadence
        job.pump()
        _chunk_tick(
            job, flags, chunk_idx,
            {"data": offsets, "requests": req_offsets},
            injector, records=polled,
        )
        chunk_idx += 1
        # 4. agreed termination: stop after idleWindows globally-idle poll
        # windows (the silence-timer termination of
        # StatisticsOperator.scala:135-142, with the timeout measured in
        # fabric-agreed windows). Before ANY pipeline exists the much
        # larger startup bound applies — a live job must not die in the
        # first second waiting for its Create to reach the requests topic.
        # (job.pipelines is identical on every process: the control plane
        # is broadcast, so this branch needs no extra collective.)
        globally_quiet = job._collective_reduce(
            [float(had_rows + len(req_lines))], "sum"
        )[0] == 0
        if overload_armed:
            # a backpressure PAUSE must not count toward the idle
            # termination bound — the fleet is overloaded, not done.
            # Collective-agreed (every process issues the reduce, armed
            # is config-identical) so the break decision stays lockstep.
            any_paused = job._collective_reduce(
                [float(data_paused[0])], "max"
            )[0] > 0
            if any_paused:
                globally_quiet = False
        ever_deployed = ever_deployed or bool(job.pipelines)
        if globally_quiet:
            idle_windows += 1
            # once ANY pipeline has existed the short bound applies —
            # a Delete of the last pipeline means the job's work is done,
            # not that it should re-enter the startup grace period
            limit = idle_limit if ever_deployed else startup_limit
            if idle_windows >= limit:
                if not ever_deployed:
                    job._warn(
                        "no Create arrived within the startup idle bound; "
                        "terminating with nothing deployed"
                    )
                break
        else:
            idle_windows = 0
    job.flush()
    consumer.close()
    if req_consumer is not None:
        req_consumer.close()


def run_distributed(argv: Optional[List[str]] = None) -> int:
    # --supervise: this process becomes the fleet supervisor instead of a
    # worker — it spawns/monitors the N worker processes and applies the
    # fixed-delay restart policy (it never initializes jax itself)
    from omldm_tpu.__main__ import parse_flags as _parse_flags

    pre_flags = _parse_flags(list(argv or []))
    if _flag_true(pre_flags, "supervise"):
        from omldm_tpu.runtime.supervisor import supervise_from_flags

        return supervise_from_flags(pre_flags)

    # this environment's jax build pins its platform list at import and
    # IGNORES the JAX_PLATFORMS env var; honor it explicitly before any
    # backend/device initialization
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except (ValueError, AttributeError) as exc:
            # a failed override must be LOUD: silently initializing on the
            # wrong backend (e.g. grabbing the TPU in a CPU smoke test)
            # makes every later failure mysterious
            print(
                f"warning: could not apply JAX_PLATFORMS="
                f"{os.environ['JAX_PLATFORMS']!r}: {exc}",
                file=sys.stderr,
            )

    flags = pre_flags
    # persistent XLA compile cache: restarted incarnations (and every
    # process after the first on a shared cache) skip recompiling the
    # collective programs — supervised recovery would otherwise pay tens
    # of seconds of compile on each restart
    from omldm_tpu.__main__ import _enable_compile_cache

    _enable_compile_cache(flags)
    if not flags.get("kafkaBrokers"):
        if "trainingData" not in flags:
            raise SystemExit("--trainingData is required in file mode")
        if "requests" not in flags and not _flag_true(flags, "restore"):
            raise SystemExit(
                "--requests is required (or --restore with a checkpoint)"
            )

    config = JobConfig(
        job_name=flags.get("jobName", "OMLDM"),
        batch_size=int(flags.get("batchSize", "256")),
        test_set_size=int(flags.get("testSetSize", "64")),
        # the distributed engine's backpressure/pressure signal
        # (runtime/overload.py backlog thresholds); unset = unarmed
        overload=flags.get("overload", ""),
        # flight recorder: decision-event journal + black-box ring dumps
        # (runtime/events.py; --flightRecorder, matching the in-process
        # CLI where bare --events names the replay file); unset = zero
        # recorder objects
        events=flags.get("flightRecorder", ""),
        blackbox_path=flags.get("blackboxPath", ""),
    )
    nproc_flag = int(flags.get("processes", "0"))
    # --processes 1 with no coordinator is a plain single-process run;
    # jax.distributed requires a coordinator address otherwise
    use_group = flags.get("coordinator") is not None and nproc_flag > 1
    job = DistributedStreamJob(
        config,
        coordinator=flags.get("coordinator") if use_group else None,
        num_processes=nproc_flag if use_group else None,
        process_id=int(flags["processId"]) if use_group else None,
    )
    # elastic-rescale knobs: --rescaleRestore false pins the strict
    # same-count restore contract; --rescaleCount is the supervisor's
    # authoritative cumulative rescale tally for Statistics
    job.rescale_restore = flags.get(
        "rescaleRestore", "true"
    ).lower() not in ("false", "0", "no")
    if "rescaleCount" in flags:
        job.rescales_performed = int(flags["rescaleCount"] or 0)
        job._rescale_count_pinned = True
    # self-healing knobs: the supervisor pins the degraded-width gauge
    # (--fleetDegraded) and --collectiveTimeoutMs arms the hang watchdog
    # (first guard entry per phase gets the --collectiveWarmupMs
    # allowance for cold XLA compiles). Unset = zero watchdog objects,
    # exact pre-PR routes.
    if "fleetDegraded" in flags:
        job.fleet_degraded = int(flags["fleetDegraded"] or 0)
    hang_ms = float(flags.get("collectiveTimeoutMs", "0") or 0)
    if hang_ms > 0:
        job.arm_hang_watchdog(
            hang_ms / 1000.0,
            warmup_s=float(flags.get("collectiveWarmupMs", "120000"))
            / 1000.0,
        )

    def _mid_deploy_beat() -> None:
        if not _heartbeat(flags, job.pid, job.heartbeat_frame()):
            job.hb_write_errors += 1

    job.beat_hook = _mid_deploy_beat
    # process 0 reads the request file; everyone else receives the
    # broadcast (passing lines from a non-0 process is ignored). On a
    # restore the manifest redeploys the pipeline map instead — the
    # requests file was fully consumed before the first snapshot.
    restoring = _flag_true(flags, "restore") and bool(
        flags.get("checkpointDir")
    ) and os.path.exists(os.path.join(flags["checkpointDir"], "LATEST"))
    if not restoring:
        _sync_requests_from_flags(job, flags)
    # --profileDir: jax.profiler trace of this worker's drive loop, one
    # trace directory PER PROCESS (a shared dir would interleave event
    # files) — the distributed twin of the single-process CLI flag
    # (__main__.py). Unset = the no-op context.
    from omldm_tpu.utils.tracing import trace as _profiler_trace

    profile_dir = flags.get("profileDir")
    if profile_dir:
        profile_dir = os.path.join(profile_dir, f"proc{job.pid}")
    with _profiler_trace(profile_dir):
        if flags.get("kafkaBrokers"):
            # a job may start with no pipelines: the Create can arrive on
            # the requests topic mid-run (startupIdleWindows bounds the
            # wait)
            _drive_kafka(job, flags)
        else:
            if not restoring and not job.pipelines:
                raise SystemExit(
                    "no pipeline deployed: the requests file must contain "
                    "at least one valid Create/Update with "
                    f"dataStructure.nFeatures ({flags.get('requests')!r})"
                )
            if job.stream_mode == "sparse" or (
                restoring and _manifest_is_sparse(flags)
            ):
                _drive_file_sparse(job, flags)
            else:
                _drive_file(job, flags)

    # post-training control-plane sync point: a second request file handled
    # after the stream drains (deterministic query-after-training — the
    # pattern the reference exercises by publishing a Query to the requests
    # topic once training data stops flowing, PipelineMap.scala:37-42).
    # Queries here see the fully-trained model; Deletes drop pipelines from
    # the final report.
    if flags.get("requestsFinal"):
        final_lines: List[str] = []
        if job.pid == 0:
            with open(flags["requestsFinal"]) as f:
                final_lines = [l.strip() for l in f if l.strip()]
        job.sync_requests(final_lines)

    # outputs: predictions per process (suffixed — a shared path would be
    # clobbered by the last writer and lose the other partitions' rows),
    # responses + performance from process 0. In Kafka mode, outputs
    # WITHOUT an explicit file sink publish to the reference's output
    # topics (predictions / responses / performance — README.md:21-26,
    # FlinkLearning.scala:137-144) through the shared ProducerSinks; an
    # explicitly-passed file sink keeps precedence over the producer,
    # exactly the single-process CLI's rule (__main__._apply_kafka_sinks).
    sinks = None
    # exactly-once-per-restart output dedupe: a process that already
    # published its topic outputs (then died before exiting cleanly)
    # leaves an EMITTED marker next to the checkpoints; the restored
    # incarnation honors it instead of double-publishing. File sinks need
    # no marker — they truncate-rewrite, so restarts self-dedupe.
    marker = None
    if flags.get("checkpointDir"):
        marker = os.path.join(flags["checkpointDir"], f"EMITTED.p{job.pid}")
        if not restoring:
            try:
                os.unlink(marker)  # stale marker from an earlier job
            except OSError:
                pass
    already_emitted = marker is not None and os.path.exists(marker)
    if flags.get("kafkaBrokers"):
        try:
            from kafka import KafkaProducer

            from omldm_tpu.runtime.kafka_io import (
                CONNECT_RETRY,
                ProducerSinks,
            )
            from omldm_tpu.utils.backoff import with_backoff

            sinks = ProducerSinks(
                with_backoff(
                    lambda: KafkaProducer(
                        bootstrap_servers=flags["kafkaBrokers"]
                    ),
                    retry_on=(Exception,),
                    policy=CONNECT_RETRY,
                )
            )
        except Exception as exc:
            # broker gone at shutdown must not lose the file outputs
            job._warn(f"output-topic producer unavailable: {exc}")
            sinks = None
    if already_emitted and sinks is not None:
        job._warn(
            "outputs already published to the topics by a previous "
            "incarnation; skipping topic publication (exactly-once)"
        )
    want_preds_file = bool(flags.get("predictionsOut"))
    publish_preds = (
        sinks is not None and not want_preds_file and not already_emitted
    )
    if want_preds_file or publish_preds:
        payloads = [
            {"mlpId": net_id, "value": v}
            for net_id, v in job.orphan_predictions
        ] + [
            {"mlpId": net_id, "value": v}
            for net_id in sorted(job.pipelines)
            for v in job.pipelines[net_id].predictions
        ]
        if want_preds_file:
            path = flags["predictionsOut"]
            if job.nproc > 1:
                path = f"{path}.p{job.pid}"
            with open(path, "w") as f:
                for obj in payloads:
                    f.write(json.dumps(obj) + "\n")
        else:
            for obj in payloads:
                sinks.on_prediction(obj)
    report = job.merged_report()
    if report is not None:
        if flags.get("responsesOut"):
            with open(flags["responsesOut"], "w") as f:
                for resp in job.responses:
                    f.write(resp.to_json() + "\n")
        elif sinks is not None and not already_emitted:
            for resp in job.responses:
                sinks.on_response(resp)
        if flags.get("performanceOut"):
            with open(flags["performanceOut"], "w") as f:
                f.write(json.dumps(report) + "\n")
        elif sinks is not None and not already_emitted:
            sinks.on_performance(report)
        print(json.dumps(report))
    if (
        marker is not None
        and sinks is not None
        and not already_emitted
        and not sinks.dropped
    ):
        # published (or deliberately skipped for file sinks): a crash
        # between here and exit must not republish on the next restore.
        # NOT written when the degraded producer dropped sends — those
        # outputs were never delivered, so a restored incarnation against
        # a healed broker must still publish them
        _atomic_write_bytes(marker, b"published\n")
    if sinks is not None:
        sinks.close()
    # final black-box dump: the terminate-time ring is this process's
    # last word in any incident bundle
    if job.events is not None:
        from omldm_tpu.runtime.events import TERMINATE

        job.events.record(TERMINATE, "drive_complete")
        job.events.dump()
    if job.watchdog is not None:
        # the collectives are done: a slow final file write must not be
        # mistaken for a wedged fabric
        job.watchdog.stop()
    return 0


if __name__ == "__main__":
    sys.exit(run_distributed(sys.argv[1:]))
