"""Multi-process streaming deployment: N ingest partitions, one global mesh.

Reference counterpart: the Flink job runs N parallel subtasks across a
cluster, fed by partitioned Kafka topics (reference: README.md:21-29,
parallelism 16 at src/main/scala/omldm/utils/DefaultJobParameters.scala:5).
The TPU-native deployment is one PYTHON PROCESS per host, joined through
``jax.distributed``:

- each process owns an ingest partition (its slice of the stream — the
  role of a Kafka partition assignment) and stages rows for its own
  mesh shard;
- the batch is assembled into ONE globally-sharded array with
  ``host_local_array`` and trained by the standard :class:`SPMDTrainer`
  step — protocol sync is the same XLA collective whether the workers
  share a host or not (ICI within a slice, DCN across);
- the CONTROL PLANE lives on process 0: Create/Update/Delete request
  lines are broadcast to every process over the collective fabric itself
  (a padded uint8 array, replicated-out jit) — control messages ride the
  same links as training traffic, no side channel;
- statistics merge with a psum-style reduction and process 0 emits the
  job report (the role of the reference's StatisticsOperator sink).

Single-process every piece degrades to local behavior, so the same code
runs a laptop test and a pod deployment. CLI:

    python -m omldm_tpu.runtime.distributed_job \
        --coordinator 127.0.0.1:9876 --processes 2 --processId 0 \
        --requests reqs.jsonl --trainingData train.jsonl \
        --performanceOut perf.jsonl
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

import numpy as np

from omldm_tpu.api.requests import Request, RequestType
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.databuffers import ArrayHoldout

CONTROL_CAP = 1 << 16  # fixed broadcast buffer: 64 KiB of request lines


def _mesh_and_procs(coordinator, num_processes, process_id):
    """Join the process group (if any) and build the global dp mesh."""
    import jax

    from omldm_tpu.parallel.multihost import initialize_multihost

    pid, nproc = initialize_multihost(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    from omldm_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(dp=n_dev, hub=1)
    return mesh, pid, nproc


class DistributedStreamJob:
    """One streaming pipeline trained across every process's devices.

    The training contract mirrors the in-process SPMD bridge: 8-of-10
    holdout split per partition (FlinkSpoke.scala:94-104 semantics, applied
    to the partition the way each Flink subtask applies it to its own
    split), staged [local_dp, B, D] micro-batches, one collective step per
    full stage across ALL processes in lockstep."""

    def __init__(
        self,
        config: JobConfig,
        coordinator: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ):
        import jax

        self.config = config
        self.mesh, self.pid, self.nproc = _mesh_and_procs(
            coordinator, num_processes, process_id
        )
        self._jax = jax
        self.dp_global = self.mesh.shape["dp"]
        self.dp_local = max(self.dp_global // self.nproc, 1)
        self.trainer = None
        self.request: Optional[Request] = None
        self.test_set: Optional[ArrayHoldout] = None
        self.holdout_count = 0
        self._steps_run = 0
        self._eval_jit = None
        self._predict_jit = None
        self._accepted_jit = None

    def _fetch_replicated(self, arr) -> np.ndarray:
        """Host copy of a REPLICATED global array: read the local shard
        (a plain device_get would try to fetch non-addressable shards of
        the multi-process array and fail)."""
        return np.asarray(arr.addressable_shards[0].data)

    # --- control plane: process-0 broadcast over the fabric ---

    def _broadcast_lines(self, lines: List[str]) -> List[str]:
        """Every process receives process 0's request lines. The payload
        travels as a [nproc, CONTROL_CAP] uint8 array assembled from
        per-process rows; a replicated-output jit hands every process row
        0 — i.e. the broadcast IS a collective on the training fabric."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        payload = "\n".join(lines).encode("utf-8") if self.pid == 0 else b""
        if len(payload) > CONTROL_CAP - 4:
            raise ValueError(
                f"control broadcast overflow ({len(payload)} bytes > "
                f"{CONTROL_CAP - 4}); split the request batch"
            )
        row = np.zeros((1, CONTROL_CAP), np.uint8)
        row[0, :4] = np.frombuffer(
            np.uint32(len(payload)).tobytes(), np.uint8
        )
        row[0, 4 : 4 + len(payload)] = np.frombuffer(payload, np.uint8)
        if self.nproc == 1:
            rows = row
        else:
            # one row per process on the dp axis; replicated output makes
            # row 0 locally addressable everywhere
            mesh_rows = np.repeat(row, self.dp_local, axis=0)
            arr = host_local_array(mesh_rows, self.mesh, P("dp"))
            take0 = jax.jit(
                lambda a: a[0],
                out_shardings=NamedSharding(self.mesh, P()),
            )
            rows = self._fetch_replicated(take0(arr))[None, :]
        n = int(np.frombuffer(rows[0, :4].tobytes(), np.uint32)[0])
        text = rows[0, 4 : 4 + n].tobytes().decode("utf-8")
        return [l for l in text.split("\n") if l]

    def sync_requests(self, lines: Optional[List[str]] = None) -> None:
        """Process 0 passes its pending request lines; every process
        deploys the same pipelines afterwards."""
        for line in self._broadcast_lines(list(lines or [])):
            request = Request.from_json(line)
            if request is None:
                continue
            if request.request in (RequestType.CREATE, RequestType.UPDATE):
                self._deploy(request)

    def _deploy(self, request: Request) -> None:
        from omldm_tpu.api.requests import TrainingConfiguration
        from omldm_tpu.parallel.spmd import SPMDTrainer

        ds = request.learner.data_structure if request.learner else None
        dim = int((ds or {}).get("nFeatures", 0))
        if dim <= 0:
            raise ValueError(
                "distributed deployment needs nFeatures on the Create "
                "(the stream width must be known before partitions start)"
            )
        tc = request.training_configuration or TrainingConfiguration(
            protocol="Synchronous"
        )
        self.request = request
        self.trainer = SPMDTrainer(
            request.learner,
            request.preprocessors or (),
            dim=dim,
            protocol=tc.protocol,
            mesh=self.mesh,
            training_configuration=tc,
            batch_size=self.config.batch_size,
        )
        self.dim = dim
        self.test_set = ArrayHoldout(self.config.test_set_size, dim)
        b = self.config.batch_size
        self._stage_cap = self.dp_local * b
        self._pend_x: List[np.ndarray] = []
        self._pend_y: List[np.ndarray] = []
        self._pend_n = 0
        self._fore_x: List[np.ndarray] = []
        self._fore_n = 0
        self.predictions: List[float] = []

    # --- data path: this process's partition only ---

    def handle_partition_rows(self, x: np.ndarray, y: np.ndarray) -> None:
        """Buffer rows from THIS process's ingest partition (holdout split
        exactly as the in-process runtime applies it per worker). Rows are
        NOT trained here: collective steps only run inside :meth:`pump`,
        where every process agrees on the round count first — a process
        stepping on local buffer fullness alone could enter a collective
        its peers never reach (lockstep deadlock)."""
        assert self.trainer is not None, "no pipeline deployed"
        n = x.shape[0]
        if n == 0:
            return
        if self.config.test:
            c = (self.holdout_count + np.arange(n)) % 10
            self.holdout_count += n
            test_mask = c >= 8
            keep_idx = np.nonzero(~test_mask)[0]
            t_idx = np.nonzero(test_mask)[0]
            ev_x, ev_y, ev_src = self.test_set.append_many(x[t_idx], y[t_idx])
            if ev_src.size:
                pos = np.concatenate([keep_idx, t_idx[ev_src]])
                order = np.argsort(pos, kind="stable")
                x = np.concatenate([x[keep_idx], ev_x])[order]
                y = np.concatenate([y[keep_idx], ev_y])[order]
            else:
                x, y = x[keep_idx], y[keep_idx]
        else:
            self.holdout_count += n
        if x.shape[0]:
            self._pend_x.append(np.asarray(x, np.float32))
            self._pend_y.append(np.asarray(y, np.float32))
            self._pend_n += x.shape[0]

    def _agree_rounds(self, local_rounds: int) -> int:
        """All processes take the MAX of their desired round counts over
        the fabric, so every one of them enters the same number of
        collective steps (short partitions contribute masked batches)."""
        if self.nproc == 1:
            return local_rounds
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        local = np.full((self.dp_local,), float(local_rounds), np.float32)
        arr = host_local_array(local, self.mesh, P("dp"))
        mx = jax.jit(
            lambda a: a.max(),
            out_shardings=NamedSharding(self.mesh, P()),
        )(arr)
        return int(float(self._fetch_replicated(mx)))

    def pump(self, final: bool = False) -> None:
        """Run the agreed number of lockstep collective steps over the
        buffered rows. Call at synchronized points of the drive loop (all
        processes pump after the same stream chunk; ``final=True`` drains
        remainders with zero-masked padding)."""
        cap = self._stage_cap
        want = (
            -(-self._pend_n // cap) if final else self._pend_n // cap
        )
        rounds = self._agree_rounds(int(want))
        if rounds == 0:
            return
        b = self.config.batch_size
        from jax.sharding import PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        buf_x = (
            np.concatenate(self._pend_x)
            if self._pend_x
            else np.zeros((0, self.dim), np.float32)
        )
        buf_y = (
            np.concatenate(self._pend_y)
            if self._pend_y
            else np.zeros((0,), np.float32)
        )
        self._pend_x, self._pend_y = [], []
        requeued = []  # (x, y) blocks refused by the SSP bound this pump
        done = 0
        for _ in range(rounds):
            rows = min(cap, buf_x.shape[0] - done)
            x = np.zeros((cap, self.dim), np.float32)
            y = np.zeros((cap,), np.float32)
            mask = np.zeros((cap,), np.float32)
            if rows > 0:
                x[:rows] = buf_x[done : done + rows]
                y[:rows] = buf_y[done : done + rows]
                mask[:rows] = 1.0
            done += max(rows, 0)
            x_d = host_local_array(
                x.reshape(self.dp_local, b, self.dim), self.mesh, P("dp")
            )
            y_d = host_local_array(
                y.reshape(self.dp_local, b), self.mesh, P("dp")
            )
            m_d = host_local_array(
                mask.reshape(self.dp_local, b), self.mesh, P("dp")
            )
            self.trainer.step(x_d, y_d, m_d, valid_count=max(rows, 0))
            self._steps_run += 1
            if self.trainer.protocol == "SSP":
                self._requeue_refused(
                    x.reshape(self.dp_local, b, self.dim),
                    y.reshape(self.dp_local, b),
                    mask.reshape(self.dp_local, b),
                    requeued,
                )
        # rebuild the pending buffer from the un-stepped tail PLUS any
        # SSP-refused rows collected during the loop (overwriting with the
        # tail alone would silently drop the requeued rows)
        self._pend_x = [buf_x[done:]] if done < buf_x.shape[0] else []
        self._pend_y = [buf_y[done:]] if done < buf_x.shape[0] else []
        self._pend_n = max(buf_x.shape[0] - done, 0)
        for rx, ry in requeued:
            self._pend_x.append(rx)
            self._pend_y.append(ry)
            self._pend_n += rx.shape[0]
        # serve buffered forecasts at the same synchronized point (their
        # rounds are agreed collectively too)
        self._pump_forecasts()

    def _requeue_refused(self, xg, yg, mg, requeued) -> None:
        """SSP pacing across processes: the device refuses batches of
        workers past the staleness bound (state untouched, accepted=0);
        each process collects ITS OWN refused rows into ``requeued`` (the
        caller merges them back into the pending buffer after the round
        loop) and corrects the fitted counter — the multi-process form of
        the SPMD bridge's host-driven requeue."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._accepted_jit is None:
            rep = NamedSharding(self.mesh, P())
            self._accepted_jit = jax.jit(
                lambda s: s["accepted"][:, 0] > 0.0, out_shardings=rep
            )
        acc = self._fetch_replicated(self._accepted_jit(self.trainer.state))
        lo = self.pid * self.dp_local
        mine = acc[lo : lo + self.dp_local]
        for w in np.nonzero(~mine)[0]:
            rows = mg[w] > 0.0
            k = int(rows.sum())
            if k == 0:
                continue
            self.trainer.note_requeued(k)
            requeued.append((
                np.asarray(xg[w][rows], np.float32),
                np.asarray(yg[w][rows], np.float32),
            ))

    def handle_forecast_rows(self, x: np.ndarray) -> None:
        """Buffer forecast rows from this partition; predictions are
        served collectively at the next :meth:`pump` (the model is
        sharded across processes, so serving is a lockstep program like
        everything else)."""
        if x.shape[0]:
            self._fore_x.append(np.asarray(x, np.float32))
            self._fore_n += x.shape[0]

    def _pump_forecasts(self) -> None:
        """Agreed rounds of collective predict over buffered forecast
        rows; every process appends ITS rows' predictions locally."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        cap = self._stage_cap
        rounds = self._agree_rounds(-(-self._fore_n // cap))
        if rounds == 0:
            return
        if self._predict_jit is None:
            t = self.trainer
            rep = NamedSharding(self.mesh, P())

            def w0(tree):
                return jax.tree_util.tree_map(lambda l: l[0, 0], tree)

            def predict_fn(state, x):
                d = x.shape[-1]
                z = x.reshape(-1, d)
                for prep, s in zip(t.preps, state["preps"]):
                    z = prep.transform(w0(s), z)
                return t.learner.predict(w0(state["params"]), z)

            self._predict_jit = jax.jit(predict_fn, out_shardings=rep)
        buf = (
            np.concatenate(self._fore_x)
            if self._fore_x
            else np.zeros((0, self.dim), np.float32)
        )
        self._fore_x, self._fore_n = [], 0
        done = 0
        for _ in range(rounds):
            rows = min(cap, buf.shape[0] - done)
            x = np.zeros((cap, self.dim), np.float32)
            if rows > 0:
                x[:rows] = buf[done : done + rows]
            x_d = host_local_array(
                x.reshape(self.dp_local, -1, self.dim), self.mesh, P("dp")
            )
            preds = self._fetch_replicated(self._predict_jit(
                self.trainer.state, x_d
            ))
            # the replicated output covers every process's rows; this
            # process's slice starts at pid * cap within the global batch
            mine = preds[self.pid * cap : self.pid * cap + max(rows, 0)]
            self.predictions.extend(float(v) for v in mine)
            done += max(rows, 0)

    def flush(self) -> None:
        """Drain, including SSP-requeued rows: repeated final pumps are
        guaranteed progress under balanced partitions (the bound refuses
        only workers ahead of the slowest, and every process keeps
        feeding its slowest workers); a livelock guard backstops
        pathological streams."""
        self.pump(final=True)
        guard = 0
        while self._agree_rounds(1 if self._pend_n else 0):
            before = self._pend_n
            self.pump(final=True)
            progressed = 1 if self._pend_n < before else 0
            if not self._agree_rounds(progressed):
                # NOBODY advanced: a dried-up partition pins the staleness
                # bound (its worker's clock cannot move) — apply the
                # termination-time release, exactly the host plane's
                # SSPParameterServer.on_terminate semantics
                self.trainer.release_stragglers()
            guard += 1
            if guard > 1000:
                raise RuntimeError(
                    "SSP drain made no progress requeuing refused rows"
                )
        self._pump_forecasts()

    # --- reporting ---

    def _evaluate_global(self) -> Tuple[float, float]:
        """Loss/score of the fleet model on the UNION of every process's
        holdout set, computed as ONE collective program: each process
        contributes its padded holdout as its mesh shard, the worker-0
        model is gathered inside the jit, and the masked means reduce
        globally — every process receives the same replicated scalars."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        cap = self.test_set.max_size
        xs_l = np.zeros((self.dp_local, cap, self.dim), np.float32)
        ys_l = np.zeros((self.dp_local, cap), np.float32)
        m_l = np.zeros((self.dp_local, cap), np.float32)
        n = len(self.test_set)
        if n:
            xs, ys = self.test_set.arrays()
            xs_l[0, :n] = xs
            ys_l[0, :n] = ys
            m_l[0, :n] = 1.0
        x_d = host_local_array(xs_l, self.mesh, P("dp"))
        y_d = host_local_array(ys_l, self.mesh, P("dp"))
        m_d = host_local_array(m_l, self.mesh, P("dp"))
        if self._eval_jit is None:
            t = self.trainer
            rep = NamedSharding(self.mesh, P())

            def w0(tree):
                return jax.tree_util.tree_map(lambda l: l[0, 0], tree)

            def eval_fn(state, x, y, mask):
                d = x.shape[-1]
                z = x.reshape(-1, d)
                yv = y.reshape(-1)
                mv = mask.reshape(-1)
                for prep, s in zip(t.preps, state["preps"]):
                    z = prep.transform(w0(s), z)
                params = w0(state["params"])
                return (
                    t.learner.loss(params, z, yv, mv),
                    t.learner.score(params, z, yv, mv),
                )

            self._eval_jit = jax.jit(eval_fn, out_shardings=(rep, rep))
        loss, score = self._eval_jit(self.trainer.state, x_d, y_d, m_d)
        return (
            float(self._fetch_replicated(loss)),
            float(self._fetch_replicated(score)),
        )

    def _global_device_counters(self) -> Tuple[int, int, int]:
        """(sum of per-worker syncs, worker-0 syncs, worker-0 steps) read
        through a replicated-output jit (the fleet state is sharded across
        processes; direct device_get cannot address remote shards)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        f = jax.jit(
            lambda s: (
                s["syncs"][:, 0].sum(),
                s["syncs"][0, 0],
                s["step"][0, 0],
            ),
            out_shardings=(rep, rep, rep),
        )
        a, b, c = f(self.trainer.state)
        return (
            int(self._fetch_replicated(a)),
            int(self._fetch_replicated(b)),
            int(self._fetch_replicated(c)),
        )

    def merged_report(self) -> Optional[dict]:
        """Global job report: host-side counters reduced over the fabric,
        device counters read collectively, score evaluated on the union
        holdout; only process 0 returns it, the others get None."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from omldm_tpu.parallel.multihost import host_local_array

        loss, score = self._evaluate_global()
        syncs_sum, syncs00, steps = self._global_device_counters()
        t = self.trainer
        # the ONE payload formula (shared with SPMDTrainer.bytes_shipped)
        sync_count, total_bytes = t.protocol_traffic_bytes(
            t.protocol, t.dp, t.flat_size, syncs_sum, syncs00, steps
        )

        vec = np.asarray(
            [self.trainer.fitted, len(self.test_set)], np.float64
        )
        if self.nproc > 1:
            rows = np.broadcast_to(
                vec[None, :] / self.dp_local, (self.dp_local, vec.size)
            ).astype(np.float64)
            arr = host_local_array(rows, self.mesh, P("dp"))
            tot = jax.jit(
                lambda a: a.sum(axis=0),
                out_shardings=NamedSharding(self.mesh, P()),
            )(arr)
            vec = self._fetch_replicated(tot)
        if self.pid != 0:
            return None
        return {
            "processes": self.nproc,
            "parallelism": self.dp_global,
            "fitted": int(round(vec[0])),
            "holdout": int(round(vec[1])),
            "loss": round(loss, 6),
            "score": round(score, 6),
            "bytesShipped": int(total_bytes),
            "syncCount": int(sync_count),
            "steps": self._steps_run,
            # LOCAL count (process 0's workers): >0 proves the SSP requeue
            # path executed in this run
            "requeuedLocal": getattr(self.trainer, "requeued_rows", 0),
        }


def run_distributed(argv: Optional[List[str]] = None) -> int:
    import argparse
    import os

    # this environment's jax build pins its platform list at import and
    # IGNORES the JAX_PLATFORMS env var; honor it explicitly before any
    # backend/device initialization
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--processes", type=int, default=None)
    ap.add_argument("--processId", type=int, default=None)
    ap.add_argument("--requests", required=True)
    ap.add_argument("--trainingData", required=True)
    ap.add_argument("--performanceOut", default=None)
    ap.add_argument("--predictionsOut", default=None)
    ap.add_argument("--batchSize", type=int, default=256)
    ap.add_argument("--testSetSize", type=int, default=64)
    args = ap.parse_args(argv)

    config = JobConfig(
        batch_size=args.batchSize, test_set_size=args.testSetSize
    )
    job = DistributedStreamJob(
        config,
        coordinator=args.coordinator,
        num_processes=args.processes,
        process_id=args.processId,
    )
    # process 0 reads the request file; everyone else receives the
    # broadcast (passing lines from a non-0 process is ignored)
    lines: List[str] = []
    if job.pid == 0:
        with open(args.requests) as f:
            lines = [l.strip() for l in f if l.strip()]
    job.sync_requests(lines)
    if job.trainer is None:
        raise SystemExit(
            "no pipeline deployed: the requests file must contain at least "
            "one Create/Update with dataStructure.nFeatures "
            f"({args.requests!r} yielded none)"
        )

    # strided partition of the stream: row i belongs to process i % nproc
    from omldm_tpu.runtime.fast_ingest import iter_file_batches

    cursor = 0
    for bx, by, bop in iter_file_batches(
        args.trainingData, job.dim, 4096
    ):
        n = bx.shape[0]
        gidx = cursor + np.arange(n)
        mine = (gidx % job.nproc) == job.pid
        cursor += n
        train = mine & (bop == 0)
        if train.any():
            job.handle_partition_rows(bx[train], by[train])
        fore = mine & (bop != 0)
        if fore.any():
            job.handle_forecast_rows(bx[fore])
        # synchronized pump point: every process sees the same chunk
        # sequence (the whole-file read models the shared Kafka offsets)
        job.pump()
    job.flush()
    if args.predictionsOut and job.predictions:
        with open(args.predictionsOut, "w") as f:
            for v in job.predictions:
                f.write(json.dumps({"mlpId": 0, "value": v}) + "\n")
    report = job.merged_report()
    if report is not None and args.performanceOut:
        with open(args.performanceOut, "w") as f:
            f.write(json.dumps(report) + "\n")
    if report is not None:
        print(json.dumps(report))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(run_distributed(sys.argv[1:]))
