"""Overload-control plane: backpressure, fair-share admission, load
shedding, and a degradation ladder.

The reference delegates overload entirely to Flink's credit-based network
backpressure (SURVEY §5): the job itself has no admission control — a slow
operator just stalls the Kafka consumer, and one hot pipeline degrades
every co-hosted tenant equally. This runtime dropped even that: the queues
added since (serving ``ServeQueue``s, ``MicroBatcher`` staging, emission
buffering, the prefetch ring) either grow unboundedly or block
indiscriminately under a burst.

This module is the controller: armed per job (``JobConfig.overload`` spec
string) and per pipeline (``trainingConfiguration.overload``), default off
= bit-identical pre-plane routes (no controller objects anywhere). Armed,
each Spoke hosts one :class:`OverloadController` that

(a) derives a PRESSURE LEVEL (OK / ELEVATED / CRITICAL, with hysteresis)
    from existing signals — serving queue depth, deferred-work backlog,
    per-tenant admission imbalance, and optionally the serve-launch p99
    from the ``StepTimer`` rings;
(b) enforces per-tenant TOKEN-BUDGET rate limits with cohort fair-share
    refill, so one hot tenant cannot starve its gang siblings. The budget
    clock is the ADMISSION STREAM itself (one tick per tenant-row
    admitted), not wall time: fairness is about shares of the spoke's
    capacity, and a count-based clock makes every shed/throttle schedule a
    pure function of the record sequence — seeded chaos bursts replay
    identically (``tests/test_overload.py`` pins this). Implementation:
    each tenant's recent admissions accumulate in a decayed counter
    (halved once per fair-share window); its remaining budget is
    ``share x fair_share - count`` — a token bucket whose refill IS the
    fair share of observed traffic, so uniform fan-out traffic can never
    flag anyone (everyone sits exactly at fair share, whatever the block
    size) while a flooded tenant's counter races ahead of the mean.
    Over-limit flags are recomputed at record/block BOUNDARIES (the
    tick), never mid-fan-out — otherwise the first tenant served each
    block would look hot purely by iteration order;
(c) climbs a DEGRADATION LADDER instead of falling over: under ELEVATED
    pressure serving ``maxBatch``/``maxDelayMs`` widen and exact staleness
    relaxes (more batching per launch), and over-limit tenants' training
    rows are deprioritized into a bounded deferral ring (drained when the
    tenant recovers or pressure clears — overflow beyond the ring is
    quarantined with reason ``throttled``); under CRITICAL pressure
    over-limit tenants' forecasts are SHED with explicit reason-coded
    dead-letter entries (``shed_overload``, carrying the tenant and queue
    depth) rather than timing out — the record's offset still commits;
(d) propagates BACKPRESSURE upstream: the job-level
    ``StreamJob.overload_level()`` fold lets the Kafka drive loops pause
    consumption (offsets uncommitted = replayable) while any spoke is
    CRITICAL — the role of Flink's credit-based backpressure, moved into
    the runtime where it can be selective instead of global.

Levels gate ACTIONS; the token buckets account continuously — so the
plane's cost when healthy is one bucket update per admission and a strided
signal scan.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from omldm_tpu.runtime.serving import ServeStats, ServingConfig

# pressure levels (the Statistics ``pressureLevel`` gauge reports the peak)
OK = 0
ELEVATED = 1
CRITICAL = 2
LEVEL_NAMES = ("OK", "ELEVATED", "CRITICAL")

# bounded shed-schedule log (determinism tests replay it) and shed-latency
# sample ring caps
SHED_LOG_CAP = 4096
SHED_LATENCY_RING = 1024

# boundary ticks between full signal re-derivations (the O(#tenants)
# rebalance): count-based, so striding costs responsiveness — 8 records
# of flag lag — without costing determinism. Forced evaluations (level
# transitions wanted NOW: idle ticks under a paused source) bypass it.
TICK_STRIDE = 8


@dataclasses.dataclass
class OverloadConfig:
    """Parsed ``trainingConfiguration.overload`` knobs for one pipeline.

    All windows/rates are in ROWS of the admission stream (count-clocked,
    see the module docstring), never seconds — except the optional
    latency-signal thresholds, which are wall-clock by nature and default
    OFF so the controller stays deterministic out of the box."""

    # --- fair-share token budget -------------------------------------
    # per-tenant accounting window, in FAIR-SHARE rows (decayed counters
    # halve once per window x n_tenants global rows; the window also
    # floors the over-limit threshold so trickle traffic never flags)
    window: int = 64
    # fair-share factor: a tenant goes OVER LIMIT when its decayed
    # admission count exceeds share x max(fair_share, window) — share
    # 2.0 tolerates a tenant running at 2x its fair share
    share: float = 2.0
    # absolute per-tenant cap: over limit when the decayed count exceeds
    # tenantRate x window rows (on top of the fair-share rule)
    tenant_rate: float = 0.0
    # --- pressure thresholds -----------------------------------------
    # hottest tenant's EXCESS over the fair-share mean, in decayed rows
    # (uniform traffic scores 0 whatever its volume)
    hot_high: float = 64.0
    hot_critical: float = 256.0
    # serving rows queued on the spoke (runtime/serving.py). ABSOLUTE and
    # opt-in (0 = off, the default): the plane's NORMAL operating depth
    # scales with tenants x maxBatch, so a deployment arming these must
    # set them above its own healthy batching depth
    queue_high: int = 0
    queue_critical: int = 0
    # deferred (throttled) rows held on the spoke
    backlog_high: int = 4096
    backlog_critical: int = 32768
    # serve-launch p99 ms over the StepTimer ring (0 = signal off — it is
    # the one wall-clock signal, so arming it trades determinism)
    p99_high_ms: float = 0.0
    p99_critical_ms: float = 0.0
    # consecutive ticks below every threshold before the level steps DOWN
    # (upward transitions are immediate) — the hysteresis that stops the
    # ladder from flapping at a threshold boundary
    cool: int = 64
    # --- degradation ladder ------------------------------------------
    # ELEVATED+: serving maxBatch/maxDelayMs multiply by this
    widen: float = 4.0
    # ELEVATED+: serving exact staleness relaxes (more batching per
    # launch at bounded model staleness)
    relax: bool = True
    # CRITICAL: over-limit tenants' forecasts shed (reason-coded
    # dead-letter entries) instead of queueing
    shed: bool = True
    # deferral-ring row cap per tenant (oldest rows beyond it are dropped
    # AND quarantined with reason ``throttled``)
    defer_cap: int = 100_000


_KNOBS = {
    "window": ("window", int),
    "share": ("share", float),
    "tenantRate": ("tenant_rate", float),
    "hotHigh": ("hot_high", float),
    "hotCritical": ("hot_critical", float),
    "queueHigh": ("queue_high", int),
    "queueCritical": ("queue_critical", int),
    "backlogHigh": ("backlog_high", int),
    "backlogCritical": ("backlog_critical", int),
    "p99HighMs": ("p99_high_ms", float),
    "p99CriticalMs": ("p99_critical_ms", float),
    "cool": ("cool", int),
    "widen": ("widen", float),
    "relax": ("relax", None),  # bool-ish
    "shed": ("shed", None),
    "deferCap": ("defer_cap", int),
}


def _parse_bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def parse_overload_spec(spec) -> Optional[OverloadConfig]:
    """dict / spec-string / True -> OverloadConfig; None / False / "" ->
    None (unarmed). Raises ValueError on unknown knobs or non-positive
    windows — the control gate turns that into a request drop, the job
    constructor into a fail-fast."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True:
        spec = {}
    if isinstance(spec, str):
        s = spec.strip()
        if s.lower() == "on":
            spec = {}
        else:
            out: dict = {}
            for part in s.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(
                        f"bad overload spec entry {part!r} (want k=v)"
                    )
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
            spec = out
    if not isinstance(spec, dict):
        raise ValueError(
            f"overload spec must be a table, got {type(spec).__name__}"
        )
    unknown = set(spec) - set(_KNOBS)
    if unknown:
        raise ValueError(f"unknown overload knob(s): {sorted(unknown)}")
    cfg = OverloadConfig()
    for key, raw in spec.items():
        field, conv = _KNOBS[key]
        value = _parse_bool(raw) if conv is None else conv(float(raw))
        setattr(cfg, field, value)
    if cfg.window < 1:
        raise ValueError("overload.window must be >= 1")
    if cfg.share <= 0:
        raise ValueError("overload.share must be > 0")
    if cfg.widen < 1.0:
        raise ValueError("overload.widen must be >= 1")
    if cfg.cool < 1:
        raise ValueError("overload.cool must be >= 1")
    if cfg.hot_critical < cfg.hot_high:
        raise ValueError("overload.hotCritical must be >= hotHigh")
    if cfg.defer_cap < 1:
        raise ValueError("overload.deferCap must be >= 1")
    return cfg


def overload_config(tc, job_spec: str = "") -> Optional[OverloadConfig]:
    """The pipeline's overload config: ``trainingConfiguration.overload``
    wins (including an explicit False = opt out of the job default);
    otherwise the job-wide ``JobConfig.overload`` spec string applies.
    None = unarmed, the exact pre-plane routes."""
    extra = getattr(tc, "extra", None) or {}
    if "overload" in extra:
        return parse_overload_spec(extra["overload"])
    return parse_overload_spec(job_spec or "")


def validate_overload(tc) -> Optional[str]:
    """Control-gate twin of :func:`overload_config`: the error string for
    an undeployable overload table, or None (mirrors the serving/codec
    gates — a bad request drops at admission instead of killing the
    job)."""
    try:
        overload_config(tc)
    except (ValueError, TypeError) as exc:
        return str(exc)
    return None


class _TenantState:
    """One tenant's admission accounting: a decayed recent-admissions
    counter (the count-clocked token budget's consumption side)."""

    __slots__ = ("count", "last_window")

    def __init__(self, clock: int, span: int):
        self.count = 0.0
        self.last_window = clock // max(span, 1)


class OverloadController:
    """Per-spoke overload controller: admission accounting, pressure
    derivation, ladder state, shed/throttle counters.

    ``spoke`` provides the signals (serving plane depth, serve timer) and
    executes the actions (defer, shed, drain) — the controller only
    decides. ``clock`` is the wall clock used by the OPTIONAL latency
    signal and shed-latency accounting; every admission/fairness decision
    runs on the count clock instead (see module docstring)."""

    def __init__(self, spoke, clock: Callable[[], float] = time.perf_counter):
        self.spoke = spoke
        self._clock = clock
        self.level = OK
        #: worst level ever reached (the Statistics pressureLevel gauge)
        self.level_peak = OK
        self._below = 0  # consecutive ticks with every signal below HIGH
        #: global admission clock: one tick per (tenant, row) admitted
        self.clock = 0
        self._tenants: Dict[int, _TenantState] = {}
        self._configs: Dict[int, OverloadConfig] = {}
        # over-limit flags + hot signal, recomputed at boundary ticks
        self._over: set = set()
        self._hot = 0.0
        self._n_live = 1
        # tick striding: full signal re-derivation every TICK_STRIDE
        # boundary ticks (count-based — deterministic)
        self._ticks = 0
        self._last_eval = 0
        #: pressure/ladder knobs: the job-level config when set, else the
        #: first armed pipeline's (per-tenant admission knobs always come
        #: from the tenant's own config)
        self.config: Optional[OverloadConfig] = None
        # deferred training rows per tenant (the ELEVATED ladder rung);
        # buffers are runtime/spoke._PauseBuffer instances, owned here so
        # they never entangle with the cooperative-pause machinery
        self.deferred: Dict[int, Any] = {}
        # per-tenant fold-once counters (reset when the spoke folds them
        # into the pipeline's hub statistics at query/terminate)
        self._shed: Dict[int, int] = {}
        self._throttled: Dict[int, int] = {}
        self._shed_lat: Dict[int, ServeStats] = {}
        #: bounded (clock, tenant, rows) shed schedule — the determinism
        #: pin's replay target
        self.shed_log: List[Tuple[int, int, int]] = []
        #: cumulative totals (survive folds; observability)
        self.total_shed = 0
        self.total_throttled = 0
        #: named external signals (e.g. prefetch occupancy): callables
        #: returning a (value, high, critical) triple, scanned at tick
        self.extra_signals: Dict[str, Callable[[], Tuple[float, float, float]]] = {}
        # degraded-serving cache: (tenant, level) -> ServingConfig
        self._eff: Dict[Tuple[int, int], ServingConfig] = {}
        # flight-recorder journal (runtime/events.EventJournal) or None:
        # ladder transitions record through it, and shed/throttle volume
        # records AGGREGATED at evaluation ticks (one event per window of
        # activity, never one per flooded record — the recorder must stay
        # far cheaper than the flood it documents)
        self.events = None
        self._ev_shed = 0
        self._ev_throttled = 0

    # --- membership ------------------------------------------------------

    def arm(self, net) -> None:
        """Register one overload-armed net (it starts with a clean,
        in-budget counter)."""
        nid = net.request.id
        cfg = net.overload
        self._configs[nid] = cfg
        if self.config is None:
            self.config = cfg
        self._tenants[nid] = _TenantState(
            self.clock, cfg.window * max(len(self._tenants) + 1, 1)
        )
        self._n_live = max(len(self._tenants), 1)
        # a re-created pipeline (Update) may carry new knobs, and its
        # over-limit flag must not survive the teardown
        self._over.discard(nid)
        self._eff = {k: v for k, v in self._eff.items() if k[0] != nid}
        net._octl = self

    def retire(self, nid: int) -> None:
        """Drop a deleted tenant's accounting (its deferred rows go with
        it, like the net's pause buffer does)."""
        self._tenants.pop(nid, None)
        self._configs.pop(nid, None)
        self.deferred.pop(nid, None)
        self._over.discard(nid)
        self._n_live = max(len(self._tenants), 1)
        self._eff = {k: v for k, v in self._eff.items() if k[0] != nid}

    @property
    def n_live(self) -> int:
        return self._n_live

    # --- fair-share token budget (count-clocked) -------------------------

    def _decay(self, st: _TenantState, cfg: OverloadConfig) -> None:
        """Halve the tenant's recent-admissions counter once per elapsed
        fair-share window (window x n_live global rows) — lazy, so the
        per-admission cost stays O(1)."""
        span = max(cfg.window * self.n_live, 1)
        w = self.clock // span
        if w > st.last_window:
            st.count *= 0.5 ** (w - st.last_window)
            st.last_window = w

    def spend(self, net, rows: int = 1) -> bool:
        """Account ``rows`` admissions for ``net``'s tenant and return its
        OVER-LIMIT flag. Accounting always runs (even at level OK) so the
        signals are warm when pressure arrives; the flag itself was
        computed at the LAST evaluated boundary tick — mid-fan-out
        recomputation would flag tenants by iteration order, not by
        load. Decay is deferred to the evaluation points (O(1) here)."""
        nid = net.request.id
        st = self._tenants.get(nid)
        self.clock += rows
        if st is None:
            return False
        st.count += rows
        return nid in self._over

    def is_over(self, nid: int) -> bool:
        """The tenant's over-limit flag as of the last boundary tick."""
        return nid in self._over

    def budget(self, nid: int) -> float:
        """Remaining fair-share token budget (share x limit base minus
        the decayed recent count; negative = over). Observability and
        tests — admission uses the boundary flags."""
        st = self._tenants.get(nid)
        if st is None:
            return 0.0
        cfg = self._configs[nid]
        self._decay(st, cfg)
        return self._limit(cfg) - st.count

    def _fair(self) -> float:
        total = 0.0
        for nid, st in self._tenants.items():
            self._decay(st, self._configs[nid])
            total += st.count
        return total / self.n_live

    def _limit(self, cfg: OverloadConfig) -> float:
        limit = cfg.share * max(self._fair(), float(cfg.window))
        if cfg.tenant_rate > 0:
            limit = min(limit, cfg.tenant_rate * cfg.window)
        return limit

    def _rebalance(self) -> float:
        """Boundary recomputation: decay every counter, recompute each
        tenant's over-limit flag against share x max(fair, window) (and
        its absolute tenantRate cap), and return the hot signal — the
        hottest tenant's EXCESS over the fair-share mean (uniform
        traffic scores 0 whatever its volume)."""
        fair = self._fair()  # decays every counter as it sums
        hot = 0.0
        over = set()
        for nid, st in self._tenants.items():
            cfg = self._configs[nid]
            excess = st.count - fair
            if excess > hot:
                hot = excess
            limit = cfg.share * max(fair, float(cfg.window))
            if st.count > limit or (
                cfg.tenant_rate > 0
                and st.count > cfg.tenant_rate * cfg.window
            ):
                over.add(nid)
        self._over = over
        self._hot = hot
        return hot

    # --- pressure --------------------------------------------------------

    def backlog_rows(self) -> int:
        return sum(len(b) for b in self.deferred.values())

    def signals(self) -> Dict[str, float]:
        """The raw pressure signals (observability + the tick input;
        ``hot`` is as of the last boundary rebalance).

        The serve-launch p99 — the one wall-clock signal — is measured
        when its threshold knob arms it (``p99HighMs > 0``, the
        pre-telemetry contract) OR when the job's telemetry plane is
        armed: arming telemetry makes the latency signal available to
        the ladder without a separate knob (the thresholds still gate
        whether it ACTS; un-thresholded it is observability only)."""
        spoke = self.spoke
        plane = getattr(spoke, "serving_plane", None)
        out = {
            "hot": self._hot,
            "queue": float(plane.queued()) if plane is not None else 0.0,
            "backlog": float(self.backlog_rows()),
        }
        cfg = self.config
        if (cfg is not None and cfg.p99_high_ms > 0) or getattr(
            spoke, "telemetry", None
        ) is not None:
            out["p99_ms"] = spoke.serve_timer.recent_p99()
        return out

    def _target_level(self) -> int:
        cfg = self.config
        if cfg is None:
            return OK
        sig = self.signals()
        pairs = [
            (sig["hot"], cfg.hot_high, cfg.hot_critical),
            (sig["queue"], cfg.queue_high, cfg.queue_critical),
            (sig["backlog"], cfg.backlog_high, cfg.backlog_critical),
        ]
        if "p99_ms" in sig:
            pairs.append(
                (sig["p99_ms"], cfg.p99_high_ms,
                 cfg.p99_critical_ms or float("inf"))
            )
        for probe in self.extra_signals.values():
            pairs.append(probe())
        level = OK
        for value, high, critical in pairs:
            if critical > 0 and value >= critical:
                return CRITICAL
            if high > 0 and value >= high:
                level = ELEVATED
        return level

    def tick(self, force: bool = False) -> Tuple[int, int]:
        """Re-derive the pressure level. Upward transitions apply
        immediately (at evaluation ticks); downward ones only after
        ``cool`` consecutive boundary ticks below every HIGH threshold
        (hysteresis). The O(#tenants) re-derivation runs every
        TICK_STRIDE boundary ticks (count-based, still deterministic);
        ``force`` evaluates now. Returns (old, new)."""
        old = self.level
        self._ticks += 1
        gap = self._ticks - self._last_eval
        if not force and gap < TICK_STRIDE:
            return old, self.level
        self._last_eval = self._ticks
        self._rebalance()
        target = self._target_level()
        if target >= self.level:
            self.level = target
            self._below = 0
        else:
            self._below += gap
            if self._below >= (self.config.cool if self.config else 1):
                self.level = target
                self._below = 0
        if self.level > self.level_peak:
            self.level_peak = self.level
        if self.events is not None:
            self._record_events(old)
        return old, self.level

    def _record_events(self, old: int) -> None:
        """Flight-recorder fold at an evaluation tick: one ``pressure``
        event per ladder transition, one aggregated ``shed``/``throttle``
        event per window with new volume (count-clocked — same-seed
        bursts replay the same event stream)."""
        from omldm_tpu.runtime.events import PRESSURE, SHED, THROTTLE

        if self.level != old:
            self.events.record(
                PRESSURE, LEVEL_NAMES[self.level], old=old, new=self.level,
                hot=round(self._hot, 3), over=sorted(self._over),
            )
        if self.total_shed > self._ev_shed:
            self.events.record(
                SHED, "overload_critical",
                rows=self.total_shed - self._ev_shed,
            )
            self._ev_shed = self.total_shed
        if self.total_throttled > self._ev_throttled:
            self.events.record(
                THROTTLE, "overload_elevated",
                rows=self.total_throttled - self._ev_throttled,
            )
            self._ev_throttled = self.total_throttled

    def idle_tick(self, rows: Optional[int] = None) -> None:
        """Advance the count clock while the source is PAUSED (upstream
        backpressure): nothing admits while paused, so without this the
        buckets would never refill, the overflow never decay, and the
        level never drop — the pause would dead-lock itself. One idle
        tick models a quarter-window of recovered capacity."""
        cfg = self.config
        if cfg is None:
            return
        if rows is None:
            rows = max(cfg.window * self.n_live // 4, 1)
        self.clock += rows
        self.tick(force=True)

    # --- degradation ladder ---------------------------------------------

    def degraded_serving(self, net) -> ServingConfig:
        """The EFFECTIVE serving config for ``net`` at the current level:
        widened maxBatch/maxDelayMs (x ``widen``) and (opt-out
        ``relax=false``) relaxed staleness — more rows per predict
        launch, bounded extra latency/staleness, instead of one launch
        per starved queue.

        Scope is the FAIRNESS story: the degradation applies to
        OVER-LIMIT tenants only — healthy tenants keep their exact
        config and latency budget while the hot tenant batches harder.
        Only a CRITICAL level with NO over-limit tenant (uniform global
        overload, e.g. an armed queue/backlog/p99 signal firing without
        imbalance) widens everyone. Cached per (tenant, level)."""
        cfg = net.serving
        if cfg is None or self.level == OK:
            return cfg
        nid = net.request.id
        if nid not in self._over and not (
            self.level >= CRITICAL and not self._over
        ):
            return cfg
        key = (nid, self.level)
        out = self._eff.get(key)
        if out is None:
            ocfg = self._configs.get(nid) or self.config
            out = ServingConfig(
                max_batch=max(int(cfg.max_batch * ocfg.widen), 1),
                max_delay_ms=cfg.max_delay_ms * ocfg.widen,
                staleness=(
                    "relaxed" if ocfg.relax else cfg.staleness
                ),
                stale_chunks=cfg.stale_chunks,
            )
            self._eff[key] = out
        return out

    # --- shed / throttle accounting -------------------------------------

    def note_shed(
        self, nid: int, rows: int, latency_ms: Optional[float] = None
    ) -> None:
        """Count ``rows`` shed forecasts. ``latency_ms`` is the
        enqueue->shed WAIT and only applies to queue-drain sheds —
        admission-time refusals never waited, and noting them as 0 would
        drown the percentile in zeros."""
        self._shed[nid] = self._shed.get(nid, 0) + rows
        self.total_shed += rows
        if latency_ms is not None:
            stats = self._shed_lat.get(nid)
            if stats is None:
                stats = self._shed_lat[nid] = ServeStats(
                    cap=SHED_LATENCY_RING
                )
            stats.note(latency_ms)
        if len(self.shed_log) < SHED_LOG_CAP:
            self.shed_log.append((self.clock, nid, rows))

    def note_throttled(self, nid: int, rows: int) -> None:
        self._throttled[nid] = self._throttled.get(nid, 0) + rows
        self.total_throttled += rows

    def take_shed(self, nid: int) -> int:
        return self._shed.pop(nid, 0)

    def take_throttled(self, nid: int) -> int:
        return self._throttled.pop(nid, 0)

    def shed_latency_p99(self, nid: int) -> float:
        stats = self._shed_lat.get(nid)
        if stats is None or stats.count == 0:
            return 0.0
        return stats.percentiles()[1]

    def drainable(self) -> List[int]:
        """Tenants whose deferred rows may re-enter the stream now: the
        whole backlog at level OK, recovered (no longer over-limit)
        tenants at any level."""
        out = []
        for nid, buf in self.deferred.items():
            if len(buf) and (self.level == OK or not self.is_over(nid)):
                out.append(nid)
        return out

    def now(self) -> float:
        return self._clock()
