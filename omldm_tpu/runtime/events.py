"""Flight recorder: structured decision-event journal, incident bundles,
watchdog alerts.

Reference counterpart: none — the reference's failure story is a black box
by design. Its only observability is the terminate-time ``JobStatistics``
report (StatisticsOperator.scala:21-150) and a ``JobTerminator`` that kills
the whole job by THROWING on the first performance record
(JobTerminator.scala:6-10): when something goes wrong there is no record of
what, where, or why. This runtime now has five planes that make autonomous
decisions (guard rollback/eviction, overload shed/pause, lifecycle
promote/rollback, autoscale rescale, transport resync/quorum-release); this
module is the causal event record connecting a symptom to the chain of
decisions that produced it.

Armed per job via ``JobConfig.events`` (or lazily by the first pipeline
whose ``trainingConfiguration.events`` table arms it) — UNSET (the default)
= zero recorder objects anywhere and every route is the exact pre-plane
code path, pinned like every prior plane. Three layers:

- :class:`EventJournal` — a typed, BOUNDED, per-process ring of decision
  events. Every plane emits structured events at its existing decision
  call sites (guard trip/rollback/eviction, delta rejection + strike,
  worker retire/re-admit, quorum release, resync, gap fast-forward,
  shed/throttle + pressure-ladder transitions, canary state-machine
  transitions, rescale decisions, supervisor restarts) with monotonic
  event ids, the count-clock position, wall time, pipeline/tenant, and a
  machine-readable ``cause``. Events that sit at a transport boundary
  carry the reliable channel's ``(networkId, seq)`` stamp (PR 4), which is
  what lets a fleet's per-process rings merge into one causal story.
- INCIDENT BUNDLES — on guard trip, supervised worker death, rescale, or
  terminate the ring dumps to JSONL under ``blackboxPath``
  (``blackbox-proc<pid>.jsonl``, atomic replace); a supervisor gathers the
  per-process dumps plus its own decision log into ONE bundle
  (``incident-*.json``) whose fleet timeline is merge-sorted on the
  transport stamps (:func:`merge_timeline`) so cross-process causality
  (worker push -> hub rejection -> worker rollback -> supervisor restart)
  reads as one ordered story. ``benchmarks/incident_report.py``
  pretty-prints a bundle.
- :class:`Watchdog` — a rule layer evaluated on metrics snapshots at
  heartbeat cadence (count-clocked ``watchdogEvery`` records, plus the
  wall-clock silence poll): throughput collapse vs a trailing window,
  serve-p99 budget breach, rising shed/rejection rate, learning-curve
  regression, heartbeat silence. Fired rules emit ``alert`` events through
  the journal AND (via the job's ``on_alert`` hook) onto the performance
  sink as ``kind="alert"`` records, with fire/clear hysteresis and an
  injectable clock. Operators get live warnings; the autoscaler/overload
  planes gain a documented place to consume them (the alert events carry
  the rule name and the breaching value).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from omldm_tpu.utils import clock as uclock

# --- event taxonomy ---------------------------------------------------------
# kinds are a closed vocabulary (the README table); causes are free-form
# machine-readable strings scoped by kind

# model-integrity guard (omldm_tpu/guard.py, runtime/spoke.py,
# protocols/base.py)
GUARD_TRIP = "guard_trip"            # worker-side divergence detected
GUARD_ROLLBACK = "guard_rollback"    # LKG rollback performed
GUARD_EVICT = "guard_evict"          # cohort member evicted to solo
DELTA_REJECTED = "delta_rejected"    # hub admission rejected a worker push
WORKER_RETIRED = "worker_retired"    # round accounting dropped a worker
WORKER_READMITTED = "worker_readmitted"
# reliable transport (runtime/messages.py, runtime/hub.py, runtime/spoke.py)
QUORUM_RELEASE = "quorum_release"    # barrier released under retirement
RESYNC = "resync"                    # authoritative state re-ship decided
GAP_RESYNC = "gap_resync"            # receive window declared a gap lost
CHANNEL_RESYNC = "channel_resync"    # worker accepted an OP_RESYNC re-ship
# overload plane (runtime/overload.py)
PRESSURE = "pressure"                # ladder level transition
SHED = "shed"                        # forecasts shed (aggregated per tick)
THROTTLE = "throttle"                # training rows deferred (aggregated)
PAUSE = "pause"                      # upstream source pause / resume
# lifecycle plane (runtime/lifecycle.py)
LIFECYCLE = "lifecycle"              # canary state-machine transition
# elastic rescale / supervision (runtime/job.py, runtime/distributed_job.py,
# runtime/supervisor.py, runtime/recovery.py)
RESCALE = "rescale"                  # parallelism change decided/agreed
RESTORE = "restore"                  # checkpoint-restore decision
RESTART = "restart"                  # supervisor restart decision
SCALE = "scale"                      # autoscale decision signaled
# self-healing fleet (runtime/selfheal.py, runtime/supervisor.py,
# runtime/distributed_job.py)
STRIKE = "strike"                    # classified failure charged to a slot
DEGRADE = "degrade"                  # shrink-to-survivors decided
PROBE = "probe"                      # re-expansion probe signaled/settled
HANG = "hang"                        # worker hang-watchdog fired (HANG_EXIT)
HEAL = "heal"                        # relaunched fleet's first heartbeat
# recorder-internal
ALERT = "alert"                      # watchdog rule fired
ALERT_CLEAR = "alert_clear"          # watchdog rule cleared (hysteresis)
INCIDENT_DUMP = "incident_dump"      # ring dumped to the black box
TERMINATE = "terminate"              # termination protocol fired

# ordering rank for events sharing one (networkId, seq) transport stamp:
# a push is rejected before its sender retires, retirement precedes the
# resync decision, and re-admission follows it — merge_timeline breaks
# same-stamp ties with this so the causal chain reads in order even when
# two processes' wall clocks disagree
_STAMP_RANK = {
    GAP_RESYNC: 0,
    DELTA_REJECTED: 1,
    WORKER_RETIRED: 2,
    RESYNC: 3,
    CHANNEL_RESYNC: 4,
    WORKER_READMITTED: 5,
}
_STAMP_RANK_DEFAULT = 6

DEFAULT_CAP = 4096
DEFAULT_TAIL = 8
DEFAULT_WATCHDOG_EVERY = 10_000
DEFAULT_CLEAR_AFTER = 2
DEFAULT_COLLAPSE_WINDOWS = 4


@dataclasses.dataclass
class EventsConfig:
    """Parsed ``JobConfig.events`` / ``trainingConfiguration.events``
    knobs."""

    # journal ring capacity (events; oldest evict)
    cap: int = DEFAULT_CAP
    # directory for JSONL ring dumps + incident bundles ("" = in-memory
    # ring only; JobConfig.blackbox_path supplies the job-wide default)
    blackbox_path: str = ""
    # per-pipeline event-tail length carried on Query responses
    tail: int = DEFAULT_TAIL
    # watchdog evaluation cadence in RECORDS (count-clocked, deterministic
    # under replay; 0 disables the rule layer entirely)
    watchdog_every: int = DEFAULT_WATCHDOG_EVERY
    # consecutive healthy evaluations before a fired rule clears
    clear_after: int = DEFAULT_CLEAR_AFTER
    # --- rules (each 0 = off) -------------------------------------------
    # fire when the current window's records/s drops below this fraction
    # of the trailing-window mean (0 < frac < 1 arms)
    collapse_frac: float = 0.0
    # trailing windows the collapse/curve rules compare against
    collapse_windows: int = DEFAULT_COLLAPSE_WINDOWS
    # fire when the serving p99 exceeds this budget (ms)
    p99_budget_ms: float = 0.0
    # fire when shed+throttled+rejected grows by at least this much in
    # one watchdog window
    shed_high: float = 0.0
    # fire when the mean latest learning-curve loss rises at least this
    # far above its trailing-window minimum
    curve_slope: float = 0.0
    # fire when no stream activity for this long (wall-clocked — the one
    # rule a stalled stream NEEDS a wall clock for; evaluated from the
    # live loop's silence poll as well as at watchdog cadence)
    silence_ms: float = 0.0

    def any_rule_armed(self) -> bool:
        return (
            0.0 < self.collapse_frac < 1.0
            or self.p99_budget_ms > 0
            or self.shed_high > 0
            or self.curve_slope > 0
            or self.silence_ms > 0
        )


_KNOBS = {
    "cap": ("cap", int),
    "blackboxPath": ("blackbox_path", str),
    "tail": ("tail", int),
    "watchdogEvery": ("watchdog_every", int),
    "clearAfter": ("clear_after", int),
    "collapseFrac": ("collapse_frac", float),
    "collapseWindows": ("collapse_windows", int),
    "p99BudgetMs": ("p99_budget_ms", float),
    "shedHigh": ("shed_high", float),
    "curveSlope": ("curve_slope", float),
    "silenceMs": ("silence_ms", float),
}


def parse_events_spec(spec) -> Optional[EventsConfig]:
    """dict / spec-string / True -> EventsConfig; None / False / "" ->
    None (unarmed). Raises ValueError on unknown knobs or nonsense values
    — the control gate turns that into a request drop, the job
    constructor into a fail-fast (the serving/overload/telemetry
    pattern)."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True:
        spec = {}
    if isinstance(spec, str):
        s = spec.strip()
        if s.lower() == "on":
            spec = {}
        else:
            out: dict = {}
            for part in s.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(
                        f"bad events spec entry {part!r} (want k=v)"
                    )
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
            spec = out
    if not isinstance(spec, dict):
        raise ValueError(
            f"events spec must be a table, got {type(spec).__name__}"
        )
    unknown = set(spec) - set(_KNOBS)
    if unknown:
        raise ValueError(f"unknown events knob(s): {sorted(unknown)}")
    cfg = EventsConfig()
    for key, raw in spec.items():
        field, conv = _KNOBS[key]
        value = str(raw) if conv is str else conv(float(raw))
        setattr(cfg, field, value)
    if cfg.cap < 1:
        raise ValueError("events.cap must be >= 1")
    if cfg.tail < 0:
        raise ValueError("events.tail must be >= 0")
    if cfg.watchdog_every < 0:
        raise ValueError("events.watchdogEvery must be >= 0")
    if cfg.clear_after < 1:
        raise ValueError("events.clearAfter must be >= 1")
    if cfg.collapse_frac < 0 or cfg.collapse_frac >= 1:
        raise ValueError("events.collapseFrac must be in [0, 1)")
    if cfg.collapse_windows < 1:
        raise ValueError("events.collapseWindows must be >= 1")
    for name in ("p99_budget_ms", "shed_high", "curve_slope", "silence_ms"):
        if getattr(cfg, name) < 0:
            raise ValueError(f"events.{name} must be >= 0")
    return cfg


def events_config(tc, job_spec: str = "") -> Optional[EventsConfig]:
    """The pipeline's events config: ``trainingConfiguration.events`` wins
    (including an explicit False = opt out under a job default); otherwise
    the job-wide ``JobConfig.events`` spec applies. None = unarmed."""
    extra = getattr(tc, "extra", None) or {}
    if "events" in extra:
        return parse_events_spec(extra["events"])
    return parse_events_spec(job_spec or "")


def validate_events(tc) -> Optional[str]:
    """Control-gate twin of :func:`events_config`: the error string for an
    undeployable events table, or None (a bad request drops at admission
    instead of killing the job)."""
    try:
        events_config(tc)
    except (ValueError, TypeError) as exc:
        return str(exc)
    return None


def events_armed_for(tc, job_spec: str = "") -> bool:
    """Whether this pipeline participates in recording (the per-pipeline
    opt-out rule, shared by hub-shard wiring at create time and the
    lazy-arming walk so the two can never diverge). A gate-validated
    table can still raise here on the belt-and-braces path — treated as
    unarmed."""
    try:
        return events_config(tc, job_spec) is not None
    except (ValueError, TypeError):
        return False


class EventJournal:
    """Typed, bounded, per-process decision-event ring.

    Every event is one JSON-shaped dict: ``id`` (monotonic within this
    journal), ``kind`` (the closed taxonomy above), ``cause``
    (machine-readable reason string), ``clock`` (the count-clock position
    — events/records processed, a pure function of the stream so replays
    stamp identically), ``wall`` (epoch seconds — the ONE
    non-deterministic field; determinism tests strip it), ``pid``, plus
    optional ``pipeline``/``tenant``/``worker``/``stamp`` and free extra
    fields. ``stamp`` is the reliable transport's ``[networkId, seq]``
    pair when the event sits at a transport boundary — the key
    :func:`merge_timeline` orders cross-process causality by.

    Recording NEVER raises and costs one dict build + deque append; the
    ring bounds memory however long the stream runs."""

    def __init__(
        self,
        cap: int = DEFAULT_CAP,
        pid: Any = 0,
        path: str = "",
        clock: Callable[[], float] = uclock.WALL,
        position: Optional[Callable[[], int]] = None,
        tail_len: int = DEFAULT_TAIL,
    ):
        self.cap = max(int(cap), 1)
        self.pid = pid
        self.path = path or ""
        self._clock = clock
        self._position = position
        self.tail_len = int(tail_len)
        # per-pipeline tail deques maintained at record time: the Query
        # path reads O(tail), not an O(cap) ring scan per fragment
        self._tails: Dict[Any, Any] = {}
        self.events: List[dict] = []
        self.total = 0          # events ever recorded (ring evicts)
        self.alerts = 0         # ALERT events ever recorded
        self.by_kind: Dict[str, int] = {}
        self.dumps_written = 0
        # ring dumps the disk refused (ENOSPC, permissions, a yanked
        # volume): the black box degrades to the in-memory ring and
        # COUNTS the drop instead of raising on the data path — the
        # counter surfaces as ``blackboxWriteErrors`` in Statistics
        self.write_errors = 0
        self._dirty = False     # events since the last dump
        # transport-stream incarnation: a LIVE rescale restarts the
        # per-net sequence counters (reused worker slots count from 0
        # again) while this journal ring persists — bumping the epoch
        # keeps merge_timeline from cross-comparing pre- and post-rescale
        # seqs under one stream key (StreamJob.rescale bumps it)
        self.epoch = 0

    def bump_epoch(self) -> None:
        self.epoch += 1

    @property
    def high_water(self) -> int:
        """The last assigned event id (0 before the first event) — the
        cross-reference dead-letter entries and heartbeat frames carry."""
        return self.total

    @property
    def dirty(self) -> bool:
        return self._dirty

    def record(
        self,
        kind: str,
        cause: str,
        pipeline: Optional[int] = None,
        tenant: Optional[int] = None,
        worker: Optional[int] = None,
        stamp: Optional[Tuple[int, int]] = None,
        **fields: Any,
    ) -> dict:
        self.total += 1
        event: dict = {
            "id": self.total,
            "kind": kind,
            "cause": cause,
            "clock": self._position() if self._position is not None else 0,
            "wall": self._clock(),
            "pid": self.pid,
        }
        if pipeline is not None:
            event["pipeline"] = pipeline
            if self.tail_len > 0:
                tail = self._tails.get(pipeline)
                if tail is None:
                    import collections

                    tail = self._tails[pipeline] = collections.deque(
                        maxlen=self.tail_len
                    )
                tail.append(event)
        if tenant is not None:
            event["tenant"] = tenant
        if worker is not None:
            event["worker"] = worker
        if stamp is not None and stamp[1] is not None:
            event["stamp"] = [int(stamp[0]), int(stamp[1])]
            if self.epoch:
                event["epoch"] = self.epoch
        if fields:
            event.update(fields)
        self.events.append(event)
        if len(self.events) > self.cap:
            del self.events[: len(self.events) - self.cap]
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        if kind == ALERT:
            self.alerts += 1
        self._dirty = True
        return event

    def tail(self, n: Optional[int] = None) -> List[dict]:
        return list(self.events if n is None else self.events[-n:])

    def tail_for(self, pipeline: int, n: Optional[int] = None) -> List[dict]:
        """The last ``tail_len`` events tagged with this pipeline — the
        ring tail a Query response carries (served from the per-pipeline
        deque, O(tail); ``n`` below the default trims further)."""
        tail = list(self._tails.get(pipeline, ()))
        if n is not None:
            tail = tail[-n:] if n else []
        return tail

    def dump_path(self) -> Optional[str]:
        if not self.path:
            return None
        return os.path.join(self.path, f"blackbox-proc{self.pid}.jsonl")

    def dump(self) -> Optional[str]:
        """Write the current ring to ``blackbox-proc<pid>.jsonl`` (atomic
        replace — a supervisor polling the black box between writes never
        reads a torn dump). Never raises; a full/odd disk degrades to the
        in-memory ring. Returns the path written, or None."""
        path = self.dump_path()
        if path is None:
            return None
        try:
            os.makedirs(self.path, exist_ok=True)
            _atomic_write_text(
                path,
                "".join(json.dumps(e) + "\n" for e in self.events),
            )
        except OSError:
            self.write_errors += 1
            return None
        self.dumps_written += 1
        self._dirty = False
        return path

    def incident(self, cause: str, **fields: Any) -> Optional[str]:
        """Record an ``incident_dump`` marker and dump the ring — the
        guard-trip / worker-death / rescale / terminate hook."""
        self.record(INCIDENT_DUMP, cause, **fields)
        return self.dump()


# --- incident bundles -------------------------------------------------------


def merge_timeline(streams: Sequence[Sequence[dict]]) -> List[dict]:
    """Merge per-process event streams into one fleet timeline.

    Base order is a stable ``(wall, pid, id)`` sort across every ring.
    Then the transport stamps repair transport-order: stamped events
    sharing one SENDER STREAM — same source ring, ``networkId``,
    ``worker``, ``hub`` shard and receive side — re-sort by
    ``(seq, rank)``, where rank
    orders the same-stamp chain push-rejection -> retirement -> resync ->
    re-admission, and land back in the same timeline slots. A chaos
    reorder that made the receiver process seq 7 before seq 5 therefore
    reads in SEND order in the bundle.

    Seq counters from INDEPENDENT streams are never cross-compared: each
    worker's channel, each direction, each restarted incarnation's ring,
    and each LIVE-RESCALE epoch within one ring (a reused worker slot's
    sequencer restarts at 0 while the journal persists — the journal
    epoch, bumped at every rescale, keeps the halves apart) counts from
    0 on its own (the reliable channel's per-stream contract,
    runtime/messages.StreamSequencer), so re-sorting across them would
    scramble unrelated history — a rescaled-in worker's seq 3 must not
    jump ahead of a veteran's seq 400. Across rings and for unstamped
    events the wall-time base order stands."""
    merged: List[Tuple[int, dict]] = []
    for epoch, events in enumerate(streams):
        for event in events:
            merged.append((epoch, event))
    merged.sort(
        key=lambda t: (
            t[1].get("wall", 0.0), str(t[1].get("pid", "")), t[1]["id"],
        )
    )
    by_stream: Dict[tuple, List[int]] = {}
    for i, (epoch, event) in enumerate(merged):
        stamp = event.get("stamp")
        if stamp is None:
            continue
        try:
            net, _seq = int(stamp[0]), int(stamp[1])
        except (TypeError, ValueError, IndexError):
            # a torn dump's garbled stamp is treated as unstamped — the
            # gather contract (never fatal) extends to the merge
            continue
        key = (
            epoch, event.get("epoch", 0), net, event.get("worker"),
            event.get("hub"), event.get("side", ""),
        )
        by_stream.setdefault(key, []).append(i)
    for positions in by_stream.values():
        ordered = sorted(
            (merged[i][1] for i in positions),
            key=lambda e: (
                int(e["stamp"][1]),
                _STAMP_RANK.get(e["kind"], _STAMP_RANK_DEFAULT),
                e.get("wall", 0.0),
                e["id"],
            ),
        )
        for slot, event in zip(positions, ordered):
            merged[slot] = (merged[slot][0], event)
    return [event for _, event in merged]


def gather_blackbox(
    path: str, min_mtime: float = 0.0
) -> List[List[dict]]:
    """Read every per-process ring dump (``blackbox-*.jsonl``) under a
    black-box directory. Torn/garbled lines are skipped, never fatal — a
    bundle built mid-crash must salvage what it can. ``min_mtime``
    excludes dumps older than the caller's run (the checkpoint-floor
    rule: a reused directory's stale rings from an earlier run — or an
    earlier, larger fleet's extra procN files — must not pollute this
    run's bundles)."""
    streams: List[List[dict]] = []
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return streams
    for name in names:
        if not (name.startswith("blackbox-") and name.endswith(".jsonl")):
            continue
        if min_mtime > 0:
            try:
                if os.path.getmtime(os.path.join(path, name)) < min_mtime:
                    continue
            except OSError:
                continue
        events: List[dict] = []
        try:
            with open(os.path.join(path, name), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(obj, dict) and "id" in obj:
                        events.append(obj)
        except OSError:
            continue
        if events:
            streams.append(events)
    return streams


def write_bundle(
    path: str,
    streams: Sequence[Sequence[dict]],
    meta: Optional[dict] = None,
) -> Optional[str]:
    """Write one incident bundle: ``{"meta", "processes", "timeline"}``
    with the fleet timeline merge-sorted on the transport stamps. Atomic
    replace; never raises (a failing disk must not take down the
    supervisor it reports for). Returns the path written, or None."""
    try:
        timeline = merge_timeline(streams)
        counts: Dict[str, int] = {}
        for event in timeline:
            counts[event.get("kind", "?")] = (
                counts.get(event.get("kind", "?"), 0) + 1
            )
        bundle = {
            "meta": dict(meta or {}),
            "processes": [
                {
                    "pid": events[0].get("pid") if events else None,
                    "events": len(events),
                }
                for events in streams
            ],
            "byKind": counts,
            "timeline": timeline,
        }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _atomic_write_text(path, json.dumps(bundle))
    except Exception:
        # the never-raises contract is absolute: a bundle is built from
        # possibly-torn crash artifacts INSIDE a supervisor's restart
        # path — no input may take down the supervisor it reports for
        return None
    return path


def _atomic_write_text(path: str, text: str) -> None:
    """tmp-write + os.replace (the dump/bundle atomicity primitive —
    readers polling between writes never see a torn file). Raises
    OSError; callers own the degrade-not-crash policy."""
    with open(path + ".tmp", "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(path + ".tmp", path)


# --- watchdog rule layer ----------------------------------------------------


class Watchdog:
    """Fire/clear alerting rules over periodic metrics snapshots.

    ``evaluate(signals, now)`` runs every armed rule against one signals
    dict (built by the job from the PR 13 metrics registry when telemetry
    is armed, from the same underlying accessors otherwise):

    - ``records``: cumulative record count (throughput-collapse rule)
    - ``serve_p99_ms``: current serving p99 (budget rule)
    - ``shed``: cumulative shed+throttled+rejected count (shed-rate rule)
    - ``loss``: mean latest learning-curve loss, or None (curve rule)
    - ``last_activity``: epoch of the last stream activity (silence rule)

    Each rule is a tiny state machine: the first breaching evaluation
    FIRES (one ``alert`` event through the journal + the ``on_alert``
    callback, which the job uses to emit a ``kind="alert"`` record on the
    performance sink); subsequent breaches hold; ``clearAfter``
    consecutive healthy evaluations CLEAR it (an ``alert_clear`` event) so
    a flapping signal cannot storm the sink. ``now`` is injectable."""

    def __init__(
        self,
        cfg: EventsConfig,
        journal: EventJournal,
        on_alert: Optional[Callable[[dict], None]] = None,
        clock: Callable[[], float] = uclock.WALL,
    ):
        self.cfg = cfg
        self.journal = journal
        self.on_alert = on_alert
        self._clock = clock
        self.evaluations = 0
        # records since the last evaluation (the count clock)
        self._records_since = 0
        # rule name -> {"firing": bool, "healthy": int}
        self._state: Dict[str, Dict[str, Any]] = {}
        # trailing history (collapse + curve rules)
        self._rates: List[float] = []
        self._losses: List[float] = []
        self._last_records: Optional[int] = None
        self._last_eval_wall: Optional[float] = None

    # --- the count clock -------------------------------------------------

    def note_records(self, n: int) -> bool:
        """Advance the count clock; True when an evaluation is due."""
        if self.cfg.watchdog_every <= 0:
            return False
        self._records_since += n
        return self._records_since >= self.cfg.watchdog_every

    # --- rule evaluation -------------------------------------------------

    def _rule(self, name: str) -> Dict[str, Any]:
        st = self._state.get(name)
        if st is None:
            st = self._state[name] = {"firing": False, "healthy": 0}
        return st

    def _settle(
        self, name: str, breach: Optional[dict], fired: List[dict]
    ) -> None:
        st = self._rule(name)
        if breach is not None:
            st["healthy"] = 0
            if not st["firing"]:
                st["firing"] = True
                event = self.journal.record(ALERT, name, **breach)
                fired.append(event)
                if self.on_alert is not None:
                    try:
                        self.on_alert(event)
                    except Exception:
                        pass  # a broken sink must not kill the job
        elif st["firing"]:
            st["healthy"] += 1
            if st["healthy"] >= self.cfg.clear_after:
                st["firing"] = False
                st["healthy"] = 0
                self.journal.record(ALERT_CLEAR, name)

    def evaluate(
        self, signals: Dict[str, Any], now: Optional[float] = None
    ) -> List[dict]:
        """One watchdog pass; returns the alert events fired. Resets the
        count clock."""
        cfg = self.cfg
        now = self._clock() if now is None else now
        self.evaluations += 1
        self._records_since = 0
        fired: List[dict] = []
        # throughput collapse: current window rate vs trailing mean
        if 0.0 < cfg.collapse_frac < 1.0:
            records = int(signals.get("records", 0))
            breach = None
            if (
                self._last_records is not None
                and self._last_eval_wall is not None
                and now > self._last_eval_wall
            ):
                rate = (records - self._last_records) / (
                    now - self._last_eval_wall
                )
                if len(self._rates) >= cfg.collapse_windows:
                    trailing = sum(self._rates) / len(self._rates)
                    if trailing > 0 and rate < cfg.collapse_frac * trailing:
                        breach = {
                            "rate": round(rate, 3),
                            "trailing": round(trailing, 3),
                        }
                self._rates.append(rate)
                if len(self._rates) > cfg.collapse_windows:
                    del self._rates[: len(self._rates) - cfg.collapse_windows]
            self._last_records = records
            self._settle("throughput_collapse", breach, fired)
        self._last_eval_wall = now
        # serving p99 budget
        if cfg.p99_budget_ms > 0:
            p99 = float(signals.get("serve_p99_ms", 0.0) or 0.0)
            self._settle(
                "serve_p99_budget",
                {"p99Ms": round(p99, 3), "budgetMs": cfg.p99_budget_ms}
                if p99 >= cfg.p99_budget_ms
                else None,
                fired,
            )
        # rising shed/rejection rate (delta per window)
        if cfg.shed_high > 0:
            shed = float(signals.get("shed", 0.0) or 0.0)
            st = self._rule("shed_rate")
            last = st.get("last")
            st["last"] = shed
            delta = shed - last if last is not None else 0.0
            self._settle(
                "shed_rate",
                {"delta": delta} if delta >= cfg.shed_high else None,
                fired,
            )
        # learning-curve regression: latest loss vs trailing minimum
        if cfg.curve_slope > 0:
            loss = signals.get("loss")
            breach = None
            if loss is not None:
                loss = float(loss)
                if len(self._losses) >= 1:
                    floor = min(self._losses)
                    if loss - floor >= cfg.curve_slope:
                        breach = {
                            "loss": round(loss, 6),
                            "floor": round(floor, 6),
                        }
                self._losses.append(loss)
                if len(self._losses) > cfg.collapse_windows:
                    del self._losses[
                        : len(self._losses) - cfg.collapse_windows
                    ]
            self._settle("curve_regression", breach, fired)
        # heartbeat silence (also evaluated by poll_silence)
        if cfg.silence_ms > 0:
            self._silence(signals.get("last_activity"), now, fired)
        return fired

    def _silence(
        self, last_activity, now: float, fired: List[dict]
    ) -> None:
        breach = None
        if last_activity is not None:
            silent_ms = (now - float(last_activity)) * 1000.0
            if silent_ms >= self.cfg.silence_ms:
                breach = {"silentMs": round(silent_ms, 1)}
        self._settle("heartbeat_silence", breach, fired)

    def poll_silence(
        self, last_activity, now: Optional[float] = None
    ) -> List[dict]:
        """Wall-clock poll for the silence rule alone (the live loop's
        check_silence hook) — the count clock cannot advance while nothing
        flows, which is exactly when this rule matters."""
        if self.cfg.silence_ms <= 0:
            return []
        now = self._clock() if now is None else now
        fired: List[dict] = []
        self._silence(last_activity, now, fired)
        return fired


class FlightRecorder:
    """Per-job flight-recorder state: the journal plus (when any rule is
    armed) the watchdog. One instance per StreamJob / distributed process
    when armed; None (the default) everywhere else."""

    def __init__(
        self,
        cfg: EventsConfig,
        pid: Any = 0,
        clock: Callable[[], float] = uclock.WALL,
        position: Optional[Callable[[], int]] = None,
        on_alert: Optional[Callable[[dict], None]] = None,
        blackbox_default: str = "",
    ):
        self.cfg = cfg
        path = cfg.blackbox_path or blackbox_default
        self.journal = EventJournal(
            cap=cfg.cap,
            pid=pid,
            path=path,
            clock=clock,
            position=position,
            tail_len=cfg.tail,
        )
        self.watchdog: Optional[Watchdog] = None
        if cfg.watchdog_every > 0 and cfg.any_rule_armed():
            self.watchdog = Watchdog(
                cfg, self.journal, on_alert=on_alert, clock=clock
            )
        # records seen (the throughput rule's cumulative count)
        self.records_seen = 0

    def note_records(self, n: int) -> bool:
        """Advance the record clock; True when a watchdog pass is due."""
        self.records_seen += n
        if self.watchdog is None:
            return False
        return self.watchdog.note_records(n)


__all__ = [
    "ALERT",
    "ALERT_CLEAR",
    "CHANNEL_RESYNC",
    "DEGRADE",
    "DELTA_REJECTED",
    "EventJournal",
    "EventsConfig",
    "FlightRecorder",
    "GAP_RESYNC",
    "GUARD_EVICT",
    "GUARD_ROLLBACK",
    "GUARD_TRIP",
    "HANG",
    "INCIDENT_DUMP",
    "LIFECYCLE",
    "PAUSE",
    "PRESSURE",
    "PROBE",
    "QUORUM_RELEASE",
    "RESCALE",
    "RESTART",
    "RESTORE",
    "RESYNC",
    "SCALE",
    "SHED",
    "STRIKE",
    "TERMINATE",
    "THROTTLE",
    "Watchdog",
    "WORKER_READMITTED",
    "WORKER_RETIRED",
    "events_config",
    "gather_blackbox",
    "merge_timeline",
    "parse_events_spec",
    "validate_events",
    "write_bundle",
]
