"""Dead-letter sink: quarantine for malformed / rejected stream input.

Reference counterpart: the reference SILENTLY drops anything its parsers
reject — ``DataInstanceParser`` swallows parse errors and ``isValid``
failures (DataPointParser.scala:13-21, DataInstanceDeserializer.scala:24-33)
and ``PipelineMap`` prints-and-drops invalid requests
(PipelineMap.scala:34,46). At "millions of users" scale a silent drop is
indistinguishable from data loss, so the TPU runtime routes every rejected
record/request here instead, tagged with a machine-readable REASON CODE
(see ``DataInstance.invalid_reason`` / ``DataInstance.parse`` for the
record codes, plus ``malformed_request`` / ``rejected_request`` on the
control stream).

The sink always keeps a bounded in-memory ring (tests and live debugging
read it); ``path`` adds an append-only JSONL file (one
``{"stream", "reason", "detail"?, "payload"}`` object per line); and
``publish`` — wired by the Kafka CLI route to
``ProducerSinks.on_dead_letter`` — forwards each entry to a ``deadLetters``
topic. Quarantine NEVER raises: a failing dead-letter file must not take
down the stream it exists to protect.

Scope: the per-record JSON event route (``StreamJob.process_event`` — the
Kafka route included, which is the boundary that faces hostile producers).
The packed/fused bulk-ingest routes parse in native code against trusted
local files and keep the reference's silent drop there; their keep/drop
decisions are pinned byte-equivalent to the Python codec by
``tests/test_parser_fuzz.py``, so nothing diverges — it is only not
*recorded* on those routes.
"""

from __future__ import annotations

import collections
import json
import sys
from typing import Any, Callable, Deque, Dict, Optional

# cap on the raw payload text preserved per entry: quarantine exists for
# diagnosis, not archival — a hostile 100 MB line must not be amplified
MAX_PAYLOAD_CHARS = 4096


class DeadLetterSink:
    """Bounded quarantine for rejected stream input, with reason codes."""

    def __init__(
        self,
        path: str = "",
        cap: int = 10_000,
        publish: Optional[Callable[[dict], None]] = None,
        request_stream: str = "requests",
    ):
        self.path = path or ""
        self.entries: Deque[dict] = collections.deque(maxlen=max(int(cap), 1))
        #: optional external publisher (e.g. a Kafka deadLetters topic)
        self.publish = publish
        #: stream name whose entries count as requests, not records (the
        #: job passes its REQUEST_STREAM constant so the record/request
        #: split cannot drift from the routing layer's naming)
        self._request_stream = request_stream
        self.record_count = 0
        self.request_count = 0
        self.by_reason: Dict[str, int] = {}
        self._fh = None
        self._file_failed = False
        #: entries the quarantine FILE refused (ENOSPC, permissions):
        #: quarantine degrades to the in-memory ring and counts the drop
        #: instead of raising on the data path (folded into the
        #: ``blackboxWriteErrors`` statistic alongside the black-box ring
        #: and heartbeat writers' drop counters)
        self.write_errors = 0
        #: flight-recorder journal (runtime/events.EventJournal), wired by
        #: the job when the recorder is armed: each quarantine entry then
        #: carries the journal's current high-water event id (``eventId``)
        #: so a quarantined record cross-references the incident bundle
        #: that explains it. None (default) = entries keep the exact
        #: pre-recorder shape.
        self.event_ring = None

    def quarantine(
        self,
        stream: str,
        payload: Any,
        reason: str,
        detail: Optional[str] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> dict:
        """Record one rejected input. Returns the entry (for callers that
        log or publish it further). Never raises. ``extra`` merges
        additional machine-readable fields into the entry — the overload
        plane's ``shed_overload``/``throttled`` entries carry the
        originating tenant and queue depth this way (the reserved keys
        stream/reason/payload/detail are never overwritten)."""
        if isinstance(payload, bytes):
            payload = payload.decode("utf-8", errors="replace")
        elif not isinstance(payload, str):
            try:
                payload = json.dumps(payload, default=str)
            except (TypeError, ValueError):
                payload = str(payload)
        entry = {
            "stream": stream,
            "reason": reason,
            "payload": payload[:MAX_PAYLOAD_CHARS],
        }
        if detail:
            entry["detail"] = detail
        if extra:
            for k, v in extra.items():
                entry.setdefault(k, v)
        if self.event_ring is not None:
            # 0 = quarantined before any decision event was recorded —
            # still informative (nothing in the bundle precedes it)
            entry.setdefault("eventId", self.event_ring.high_water)
        self.entries.append(entry)
        if stream == self._request_stream:
            self.request_count += 1
        else:
            self.record_count += 1
        self.by_reason[reason] = self.by_reason.get(reason, 0) + 1
        self._write(entry)
        if self.publish is not None:
            try:
                self.publish(entry)
            except Exception as exc:  # a dead topic must not kill the job
                print(
                    f"warning: dead-letter publish failed: {exc}",
                    file=sys.stderr,
                )
                self.publish = None
        return entry

    @property
    def total(self) -> int:
        return self.record_count + self.request_count

    def _write(self, entry: dict) -> None:
        if not self.path or self._file_failed:
            return
        try:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()
        except OSError as exc:
            # degrade to in-memory only, once, loudly
            self.write_errors += 1
            self._file_failed = True
            print(
                f"warning: dead-letter file {self.path!r} unwritable "
                f"({exc}); quarantine continues in memory only",
                file=sys.stderr,
            )

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
