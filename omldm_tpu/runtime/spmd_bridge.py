"""SPMDBridge: host one streaming pipeline on the collective SPMD engine.

The streaming runtime's host plane multiplexes pipelines across in-process
spokes (message-passing protocol sync, SURVEY.md §3.3); this bridge is the
second deployment mode: a pipeline whose ``trainingConfiguration`` sets
``{"engine": "spmd"}`` trains on :class:`omldm_tpu.parallel.SPMDTrainer`
instead — every data-parallel worker is a mesh shard and protocol sync is
an XLA collective over ICI, while the pipeline keeps the EXACT streaming
contract of a host-plane pipeline: 8-of-10 holdout sampling, micro-batch
training of evicted/kept records, forecasting predictions, bucketed query
responses, the responseId -1 termination fragments (one per configured
worker so the parallelism x pipelines countdown is preserved,
StatisticsOperator.scala:109), and protocol statistics with
bytesShipped/modelsShipped accounting from the collective call sites.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from omldm_tpu.api.data import FORECASTING, DataInstance, Prediction
from omldm_tpu.api.requests import Request
from omldm_tpu.api.responses import TERMINATION_RESPONSE_ID, QueryResponse
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.parallel.mesh import make_mesh
from omldm_tpu.parallel.spmd import SPMD_PROTOCOLS, SPMDTrainer
from omldm_tpu.runtime.databuffers import ArrayHoldout
from omldm_tpu.runtime.spoke import PREDICT_BATCH
from omldm_tpu.runtime.vectorizer import F32_MAX, Vectorizer


# flush remainders pad to this sub-batch instead of a full dp*B group
# (a 1-row tail no longer ships half a megabyte of zeros)
TAIL_BATCH = 256


def _resident_absorb(sx, sy, hx, hy, bx, by, ev_slot, ev_dst, keep_src,
                     keep_dst, hold_dst):
    """One device-resident ingest segment: gather the holdout rows the
    segment evicts (before their slots are overwritten), scatter them and
    the kept rows into the stage at their stream-order ranks, and scatter
    the segment's test rows into their holdout ring slots. All index
    arrays are host-computed; padding lanes carry out-of-range
    destinations, which ``mode="drop"`` discards."""
    sx = sx.at[ev_dst].set(hx[ev_slot], mode="drop")
    sy = sy.at[ev_dst].set(hy[ev_slot], mode="drop")
    sx = sx.at[keep_dst].set(bx[keep_src], mode="drop")
    sy = sy.at[keep_dst].set(by[keep_src], mode="drop")
    hx = hx.at[hold_dst].set(bx, mode="drop")
    hy = hy.at[hold_dst].set(by, mode="drop")
    return sx, sy, hx, hy


def _resident_seg_rows(hold_cap: int, test_enabled: bool) -> int:
    """Segment width for the resident kernel. Scatter destinations must be
    distinct within one call, so a segment may not carry more test rows
    than the holdout ring holds; the worst case over cycle phases for a
    window of m rows is 2*(m//10) + min(m%10, 2)."""
    if not test_enabled:
        return 4096
    m = 5 * hold_cap
    while m > 1 and (2 * (m // 10) + min(m % 10, 2)) > hold_cap:
        m -= 1
    return max(m, 1)


class _ResidentIngest:
    """Device-resident stage + holdout for :class:`SPMDBridge`.

    When armed (``JobConfig.ingest`` with ``device:on``), the staging pad
    and the holdout ring live as jax arrays; the host computes only the
    O(n) index arithmetic per block (the exact ``_train_rows`` /
    ``ArrayHoldout.append_many`` semantics, counters stay host-side) and
    one jitted gather/scatter moves the rows. A full stage launches
    ``step_many_dense`` directly on the resident arrays — no host staging
    copy, no per-batch holdout filtering on the host. Partial drains
    (flush/snapshot) sync back through the bridge's ordinary host path so
    the fitted/holdout row order stays bit-identical to the unarmed
    route."""

    def __init__(self, bridge: "SPMDBridge"):
        self.bridge = bridge
        self.seg = _resident_seg_rows(
            bridge.test_set.max_size, bool(bridge.config.test)
        )
        self.sx = jnp.zeros((bridge._stage_cap, bridge.dim), jnp.float32)
        self.sy = jnp.zeros((bridge._stage_cap,), jnp.float32)
        self.hx = jnp.asarray(bridge.test_set._x)
        self.hy = jnp.asarray(bridge.test_set._y)
        self._kernel = jax.jit(_resident_absorb, donate_argnums=(0, 1, 2, 3))

    # --- hot path ---

    def absorb(self, x: np.ndarray, y: np.ndarray) -> None:
        """Resident twin of ``_train_rows`` + ``_stage_rows``: identical
        holdout cycle, eviction order, and stage fill order, with the row
        movement on device."""
        br = self.bridge
        ts = br.test_set
        n = x.shape[0]
        x = np.ascontiguousarray(x, np.float32)
        y = np.ascontiguousarray(y, np.float32)
        cap = br._stage_cap
        H = ts.max_size
        i = 0
        while i < n:
            m = min(self.seg, n - i)
            if br.config.test:
                c = (br.holdout_count + np.arange(m)) % 10
                test_mask = c >= 8
                # a test row emits a train row only once the ring is full
                # at its turn (it evicts the oldest holdout point)
                free = H - ts._n
                emits = np.where(test_mask, np.cumsum(test_mask) > free, True)
            else:
                test_mask = np.zeros(m, bool)
                emits = np.ones(m, bool)
            train_cum = np.cumsum(emits)
            room = cap - br._stage_n
            if train_cum.size and train_cum[-1] > room:
                # split where the stage fills exactly; trailing rows that
                # emit nothing may ride along (harmless), emitters may not
                m = int(np.searchsorted(train_cum, room, side="right"))
                test_mask = test_mask[:m]
            t_idx = np.nonzero(test_mask)[0]
            keep_idx = np.nonzero(~test_mask)[0]
            fill = min(H - ts._n, t_idx.size)
            k2 = t_idx.size - fill
            head = ts._head
            slot_fill = (head + ts._n + np.arange(fill)) % H
            slot_ev = (head + np.arange(k2)) % H
            hold_dst = np.full(self.seg, H, np.int32)
            hold_dst[t_idx[:fill]] = slot_fill
            hold_dst[t_idx[fill:]] = slot_ev
            # evicted points re-enter training at the evicting row's slot:
            # same stable order as _train_rows' argsort re-merge
            pos = np.concatenate([keep_idx, t_idx[fill:]])
            order = np.argsort(pos, kind="stable")
            rank = np.empty(pos.size, np.int64)
            rank[order] = np.arange(pos.size)
            base = br._stage_n
            keep_src = np.zeros(self.seg, np.int32)
            keep_dst = np.full(self.seg, cap, np.int32)
            keep_src[: keep_idx.size] = keep_idx
            keep_dst[: keep_idx.size] = base + rank[: keep_idx.size]
            ev_slot = np.zeros(self.seg, np.int32)
            ev_dst = np.full(self.seg, cap, np.int32)
            ev_slot[:k2] = slot_ev
            ev_dst[:k2] = base + rank[keep_idx.size :]
            bx = np.zeros((self.seg, br.dim), np.float32)
            by = np.zeros((self.seg,), np.float32)
            bx[:m] = x[i : i + m]
            by[:m] = y[i : i + m]
            self.sx, self.sy, self.hx, self.hy = self._kernel(
                self.sx, self.sy, self.hx, self.hy,
                bx, by, ev_slot, ev_dst, keep_src, keep_dst, hold_dst,
            )
            ts._n += fill
            ts._head = (head + k2) % H
            br.holdout_count += m
            br._stage_n = base + pos.size
            if br._stage_n >= cap:
                self._launch_full()
            i += m

    def _launch_full(self) -> None:
        br = self.bridge
        b = br.config.batch_size
        xs = self.sx.reshape(br.chain, br.dp, b, br.dim)
        ys = self.sy.reshape(br.chain, br.dp, b)
        br.trainer.step_many_dense(xs, ys)
        br._stage_n = 0

    # --- drains / sync (rare paths go through the host route) ---

    def drain_to_host(self) -> None:
        """Flush a partial stage through the bridge's host tail path
        (whole [dp, B] groups + padded TAIL_BATCH remainder) so partial
        launches are bit-identical to the unarmed route."""
        br = self.bridge
        n = br._stage_n
        br._stage_n = 0
        if n == 0:
            return
        br._train_buffer(np.asarray(self.sx[:n]), np.asarray(self.sy[:n]), n)

    def sync_host(self) -> None:
        """Copy the resident holdout/stage back into the host mirrors
        (checkpoint snapshots read them)."""
        br = self.bridge
        ts = br.test_set
        ts._x[...] = np.asarray(self.hx)
        ts._y[...] = np.asarray(self.hy)
        n = br._stage_n
        br._stage_x[:n] = np.asarray(self.sx[:n])
        br._stage_y[:n] = np.asarray(self.sy[:n])

    def push_from_host(self) -> None:
        """Re-upload the host mirrors (checkpoint restore writes them)."""
        br = self.bridge
        self.hx = jnp.asarray(br.test_set._x)
        self.hy = jnp.asarray(br.test_set._y)
        self.sx = jnp.asarray(br._stage_x, jnp.float32)
        self.sy = jnp.asarray(br._stage_y, jnp.float32)

    def eval_arrays(self):
        """Holdout eval inputs straight from the resident ring — same
        oldest-first order and zero padding as ``ArrayHoldout.arrays`` +
        the host pad, without the device round trip."""
        ts = self.bridge.test_set
        cap = ts.max_size
        idx = jnp.asarray((ts._head + np.arange(cap)) % cap)
        mask = jnp.asarray(
            (np.arange(cap) < ts._n).astype(np.float32)
        )
        xs = jnp.where(mask[:, None] > 0, self.hx[idx], 0.0)
        ys = jnp.where(mask > 0, self.hy[idx], 0.0)
        return xs, ys, mask


def spmd_engine_requested(request: Request) -> bool:
    return (
        str(request.training_configuration.extra.get("engine", "")).lower()
        == "spmd"
    )


def spmd_engine_supported(request: Request) -> bool:
    """The engine hosts the 6 collective protocols with device learners;
    anything else falls back to the host plane. Sparse (padded-COO)
    pipelines deploy on :class:`SparseSPMDBridge`."""
    protocol = request.training_configuration.protocol
    learner = request.learner.name if request.learner else ""
    return protocol in SPMD_PROTOCOLS and learner not in ("HT",)


def make_spmd_bridge(request: Request, dim, config, emit_prediction,
                     emit_response) -> "SPMDBridge":
    """Bridge factory: padded-COO pipelines get the sparse variant."""
    ds = request.learner.data_structure if request.learner else None
    cls = SparseSPMDBridge if (ds and ds.get("sparse")) else SPMDBridge
    return cls(request, dim, config, emit_prediction, emit_response)


def _line_aligned_chunks(path: str, chunk_bytes: int, start_offset: int = 0):
    """Yield (buf, stop) line-aligned regions of a JSON-lines file from one
    reusable read buffer (readinto + carried partial line; grows when a
    single line exceeds the buffer). Shared by the dense and sparse bulk
    ingest routes so the subtle carry logic exists once. ``start_offset``
    resumes mid-file at a known line-aligned byte position (checkpoint
    cursors record one)."""
    buf = bytearray(chunk_bytes)
    carry = 0
    with open(path, "rb") as f:
        if start_offset:
            f.seek(start_offset)
        while True:
            if carry >= len(buf):  # one line longer than the buffer
                buf.extend(bytes(len(buf)))
            n = f.readinto(memoryview(buf)[carry:])
            if not n:
                break
            end = carry + n
            cut = buf.rfind(b"\n", 0, end)
            if cut < 0:
                carry = end
                continue
            yield buf, cut + 1
            carry = end - (cut + 1)
            if carry:
                buf[:carry] = buf[cut + 1 : end]
        if carry:
            buf[carry : carry + 1] = b"\n"
            yield buf, carry + 1


class _OverlapDispatcher:
    """Bounded producer/consumer scaffolding shared by the dense and
    sparse double-buffered ingest routes: a pool of ``depth`` spare stage
    sets bounds look-ahead memory (the parse thread blocks on ``swap``
    when the device is behind), a work queue dispatches sets strictly in
    order on one daemon thread, and worker exceptions surface to the
    parse thread — the set returns to the pool even when the launch
    raises, so the producer can never deadlock in ``swap`` instead of
    seeing the error."""

    def __init__(self, make_set, depth: int, train):
        import queue
        import threading

        self.pool: "queue.Queue" = queue.Queue()
        for _ in range(max(depth, 1)):
            self.pool.put(make_set())
        self.work: "queue.Queue" = queue.Queue()
        self.errors: List[BaseException] = []
        self._train = train

        def worker():
            while True:
                item = self.work.get()
                try:
                    if item is None:
                        return
                    stage_set, n = item
                    if not self.errors:
                        self._train(stage_set, n)
                except BaseException as exc:  # surfaced to the producer
                    self.errors.append(exc)
                finally:
                    if item is not None:
                        self.pool.put(item[0])
                    self.work.task_done()

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def submit(self, stage_set, n: int):
        """Queue a filled set, return a fresh one from the pool. Raises
        any pending worker error instead of queueing more work onto a
        dead pipeline."""
        if self.errors:
            raise self.errors[0]
        self.work.put((stage_set, n))
        return self.pool.get()

    def quiesce(self) -> None:
        """Drain the queue (producer-side trainer access needs the worker
        idle); re-raise any worker error."""
        self.work.join()
        if self.errors:
            raise self.errors[0]

    def close(self) -> None:
        self.work.put(None)
        self._thread.join()

    def raise_pending(self) -> None:
        if self.errors:
            raise self.errors[0]


class SPMDBridge:
    """One pipeline, streaming in, trained across the device mesh."""

    def __init__(
        self,
        request: Request,
        dim: int,
        config: JobConfig,
        emit_prediction: Callable[[Prediction], None],
        emit_response: Callable[[QueryResponse], None],
    ):
        self.request = request
        self.config = config
        self._emit_prediction = emit_prediction
        self._emit_response = emit_response
        tc = request.training_configuration
        n_dev = len(jax.devices())
        hub = max(int(tc.hub_parallelism), 1)
        if hub > n_dev:
            hub = 1
        # as many mesh workers as devices allow, capped by the job's
        # configured parallelism (the virtual worker count for statistics)
        dp = max(min(config.parallelism, n_dev // hub), 1)
        self.trainer = SPMDTrainer(
            request.learner,
            request.preprocessors or (),
            dim=dim,
            protocol=tc.protocol,
            mesh=make_mesh(dp=dp, hub=hub),
            training_configuration=tc,
            batch_size=config.batch_size,
        )
        self.dp = dp
        hash_dims = int(tc.extra.get("hashDims", 0))
        self.vectorizer = Vectorizer(dim, hash_dims)
        self.dim = dim
        self.test_set = ArrayHoldout(config.test_set_size, dim)
        self.holdout_count = 0
        # staged rows fill a [chain * dp * B, D] buffer; a full buffer is
        # one chained step_many launch (amortizes dispatch — the per-launch
        # cost dominates through the TPU tunnel and is real on any host)
        self.chain = max(int(tc.extra.get("stageChain", 8)), 1)
        b = config.batch_size
        # optional narrow feed dtype: float16 staging halves host->device
        # bytes. This is LOSSY quantization of the inputs, not a transport
        # trick: features/targets round to fp16 (~3 decimal digits,
        # |x| <= 65504) before the on-device f32 cast. Opt in only for
        # streams whose value range tolerates it.
        feed = str(tc.extra.get("feedDtype", "float32"))
        if feed not in ("float32", "float16"):
            raise ValueError(f"feedDtype must be float32|float16, got {feed!r}")
        self.feed_dtype = np.dtype(feed)
        # SSP paces per-worker progress: every launch must surface its
        # accept flags so refused batches can be requeued — no chaining.
        # Asynchronous CONSUMES every offered batch (allowed = has_data),
        # so it keeps the chained bulk path and never checks flags.
        self._paced = tc.protocol == "SSP"
        if self._paced:
            self.chain = 1
        self._stage_cap = self.chain * dp * b
        self._stage_x = np.zeros((self._stage_cap, dim), self.feed_dtype)
        self._stage_y = np.zeros((self._stage_cap,), self.feed_dtype)
        self._stage_n = 0
        # armed by enable_resident_ingest() (JobConfig.ingest device:on)
        self._resident: Optional[_ResidentIngest] = None

    # --- data path ---

    def handle_data(self, inst: DataInstance) -> None:
        x = self.vectorizer.vectorize(inst)
        if inst.operation == FORECASTING:
            xb = np.zeros((PREDICT_BATCH, self.dim), np.float32)
            xb[0] = x
            preds = self.trainer.predict(xb)
            self._emit_prediction(
                Prediction(self.request.id, inst, float(preds[0]))
            )
            return
        y = (
            0.0 if inst.target is None
            else min(max(float(inst.target), -F32_MAX), F32_MAX)
        )
        # 20% holdout: counts 8,9 of each 0-9 cycle (FlinkSpoke.scala:94-104)
        # — the single-record case of _train_rows (which also routes through
        # the resident stage when armed)
        self._train_rows(x[None, :], np.asarray([y], np.float32))

    def handle_batch(
        self, x: np.ndarray, y: np.ndarray, op: np.ndarray
    ) -> None:
        """Bulk equivalent of handle_data for pre-vectorized rows (the C++
        ingest path): same holdout cycle and staging order as feeding the
        rows one at a time, but vectorized end to end."""
        n = x.shape[0]
        if n == 0:
            return
        if x.shape[1] != self.dim:
            w = min(x.shape[1], self.dim)
            out = np.zeros((n, self.dim), np.float32)
            out[:, :w] = x[:, :w]
            x = out
        f_idx = np.nonzero(op != 0)[0]
        if f_idx.size:
            # serve each forecast at its stream position (train the rows
            # before it first) so packed ordering matches per-record
            prev = 0
            for f in f_idx:
                f = int(f)
                if f > prev:
                    self._train_rows(x[prev:f], y[prev:f])
                xb = np.zeros((PREDICT_BATCH, self.dim), np.float32)
                xb[0] = x[f]
                preds = self.trainer.predict(xb)
                inst = DataInstance(
                    numerical_features=x[f].tolist(),
                    operation=FORECASTING,
                )
                self._emit_prediction(
                    Prediction(self.request.id, inst, float(preds[0]))
                )
                prev = f + 1
            if prev < n:
                self._train_rows(x[prev:], y[prev:])
            return
        self._train_rows(x, y)

    def _train_rows(self, x: np.ndarray, y: np.ndarray) -> None:
        """Holdout-split a run of training rows, then stage them."""
        n = x.shape[0]
        if n == 0:
            return
        if self._resident is not None:
            self._resident.absorb(x, y)
            return
        if self.config.test:
            c = (self.holdout_count + np.arange(n)) % 10
            self.holdout_count += n
            test_mask = c >= 8
            keep_idx = np.nonzero(~test_mask)[0]
            t_idx = np.nonzero(test_mask)[0]
            ev_x, ev_y, ev_src = self.test_set.append_many(x[t_idx], y[t_idx])
            if ev_src.size:
                # evicted points re-enter training at the evicting row's slot
                pos = np.concatenate([keep_idx, t_idx[ev_src]])
                order = np.argsort(pos, kind="stable")
                x = np.concatenate([x[keep_idx], ev_x])[order]
                y = np.concatenate([y[keep_idx], ev_y])[order]
            else:
                x = x[keep_idx]
                y = y[keep_idx]
        else:
            self.holdout_count += n
        self._stage_rows(x, y)

    def _stage_rows(self, x: np.ndarray, y: np.ndarray) -> None:
        i = 0
        n = x.shape[0]
        while i < n:
            take = min(self._stage_cap - self._stage_n, n - i)
            self._stage_x[self._stage_n : self._stage_n + take] = x[i : i + take]
            self._stage_y[self._stage_n : self._stage_n + take] = y[i : i + take]
            self._stage_n += take
            i += take
            if self._stage_n >= self._stage_cap:
                self._train_staged(full=True)

    def _train_staged(self, full: bool = False) -> None:
        """Launch the staged rows of the bridge's own stage buffer."""
        if self._resident is not None:
            self._resident.drain_to_host()
            return
        n = self._stage_n
        self._stage_n = 0
        self._train_buffer(self._stage_x, self._stage_y, n, full)

    def _train_buffer(
        self, buf_x: np.ndarray, buf_y: np.ndarray, n: int, full: bool = False
    ) -> None:
        """Launch ``n`` staged rows from an EXPLICIT buffer pair (the
        double-buffered ingest owns several): a full stage is one chained
        mask-free step_many_dense launch of ``chain`` [dp, B, D] steps (the
        stage buffer is exactly chain*dp*B rows, so every row is valid and
        no mask ships); a partial stage (flush) runs whole [dp, B] groups
        as single steps and the remainder through a small [dp, TAIL_B]
        padded step instead of padding a whole dp*B group for a handful of
        rows."""
        if n == 0:
            return
        # COPY before handing rows to the device: dispatch is async and
        # jax may alias numpy argument buffers zero-copy (observed on the
        # CPU backend — reusing the stage buffer mid-read corrupted rows
        # nondeterministically), and under SSP refused batches re-enter
        # the reused stage anyway. The memcpy is small next to the parse.
        b = self.config.batch_size
        group = self.dp * b
        if full and not self._paced:
            xs = np.array(buf_x, copy=True).reshape(
                self.chain, self.dp, b, self.dim
            )
            ys = np.array(buf_y, copy=True).reshape(self.chain, self.dp, b)
            self.trainer.step_many_dense(xs, ys)
            return
        stage_x = buf_x[:n].copy()
        stage_y = buf_y[:n].copy()
        done = 0
        while n - done >= group:
            xg = stage_x[done : done + group].reshape(self.dp, b, self.dim)
            yg = stage_y[done : done + group].reshape(self.dp, b)
            self.trainer.step(
                xg.astype(np.float32, copy=False),
                yg.astype(np.float32, copy=False),
                np.ones((self.dp, b), np.float32),
                valid_count=group,
            )
            self._requeue_refused(xg, yg, None)
            done += group
        tail_b = min(b, TAIL_BATCH)
        tail_group = self.dp * tail_b
        while n - done > 0:
            rem = min(n - done, tail_group)
            x = np.zeros((tail_group, self.dim), np.float32)
            y = np.zeros((tail_group,), np.float32)
            mask = np.zeros((tail_group,), np.float32)
            x[:rem] = stage_x[done : done + rem]
            y[:rem] = stage_y[done : done + rem]
            mask[:rem] = 1.0
            # stripe rows across workers (row i -> slot i % dp); under SSP
            # pacing, slots map SLOWEST-CLOCK-FIRST onto workers — the
            # slowest worker always satisfies the bound, so every tail pass
            # is guaranteed progress and short tails feed the laggards that
            # gate min_clock instead of starving them
            xg = np.ascontiguousarray(
                x.reshape(tail_b, self.dp, self.dim).transpose(1, 0, 2)
            )
            yg = np.ascontiguousarray(y.reshape(tail_b, self.dp).T)
            mg = np.ascontiguousarray(mask.reshape(tail_b, self.dp).T)
            if self._paced:
                order = np.argsort(self.trainer.worker_clocks(), kind="stable")
                inv = np.empty_like(order)
                inv[order] = np.arange(self.dp)
                xg, yg, mg = xg[inv], yg[inv], mg[inv]
            self.trainer.step(xg, yg, mg, valid_count=rem)
            self._requeue_refused(xg, yg, mg)
            done += rem

    def _requeue_refused(self, xg, yg, mg) -> None:
        """SSP pacing: re-stage the rows of workers whose batch the device
        refused (staleness bound) and correct the fitted counter."""
        if not self._paced:
            return
        acc = self.trainer.last_accepted()
        if acc.all():
            return
        for w in np.nonzero(~acc)[0]:
            rows = (
                np.ones(yg.shape[1], bool) if mg is None else mg[w] > 0.0
            )
            k = int(rows.sum())
            if k == 0:
                continue
            self.trainer.note_requeued(k)
            self._stage_rows(
                np.asarray(xg[w][rows], np.float32),
                np.asarray(yg[w][rows], np.float32),
            )

    def flush(self) -> None:
        """Drain the stage. Under SSP pacing, refused rows re-enter the
        stage; repeated passes are guaranteed progress (tail slots map
        slowest-first, and the slowest worker always satisfies the bound),
        so the drain terminates — the quiesce analogue of the host plane's
        SSPParameterServer.on_terminate release."""
        self._train_staged()
        while self._paced and self._stage_n:
            before = self._stage_n
            self._train_staged()
            if self._stage_n >= before:
                raise RuntimeError(
                    "SSP flush made no progress draining refused rows"
                )

    # --- checkpoint buffer snapshot (polymorphic: sparse overrides) ---

    def snapshot_buffers(self) -> dict:
        """Holdout + staged rows for a job checkpoint."""
        if self._resident is not None:
            self._resident.sync_host()
        test_x, test_y = self.test_set.arrays()
        return {
            "test_x": test_x.copy(),
            "test_y": test_y.copy(),
            "stage_x": np.asarray(
                self._stage_x[: self._stage_n], np.float32
            ).copy(),
            "stage_y": np.asarray(
                self._stage_y[: self._stage_n], np.float32
            ).copy(),
        }

    def restore_buffers(self, bd: dict) -> None:
        if self._resident is not None:
            # restore on the host mirrors (the rare path), then re-upload
            res, self._resident = self._resident, None
            res.sync_host()
            try:
                self.restore_buffers(bd)
            finally:
                self._resident = res
                res.push_from_host()
            return
        if bd["test_x"].shape[0]:
            self.test_set.append_many(bd["test_x"], bd["test_y"])
        if bd["stage_x"].shape[0]:
            self._stage_rows(bd["stage_x"], bd["stage_y"])

    # --- fused file ingest (C parse -> holdout -> stage, zero numpy) ---

    def supports_fused_ingest(self) -> bool:
        """The fused C loop writes float32 rows straight into the staging
        buffers; fp16 feeds and missing-toolchain hosts use the packed
        numpy route instead. A resident stage lives on device — the C loop
        cannot write it, so the packed route (which feeds _train_rows and
        thereby the resident kernel) carries those jobs."""
        from omldm_tpu.ops.native import fast_parser_available

        return (
            self.feed_dtype == np.float32
            and self._resident is None
            and fast_parser_available()
        )

    # --- device-resident stage/holdout (JobConfig.ingest device:on) ---

    def supports_resident_ingest(self) -> bool:
        """Resident stage/holdout needs the chained mask-free launch path:
        float32 feed, no SSP pacing (refused rows must re-enter a host
        stage)."""
        return self.feed_dtype == np.float32 and not self._paced

    def enable_resident_ingest(self) -> bool:
        """Arm the device-resident stage + holdout ring. Returns False
        (and stays on the host route) for bridges the resident path cannot
        serve. Safe to call before any data flows; arming mid-stream would
        strand staged host rows, so it is refused then."""
        if self._resident is not None:
            return True
        if not self.supports_resident_ingest():
            return False
        if self._stage_n or len(self.test_set):
            return False
        self._resident = _ResidentIngest(self)
        return True

    def _fused_stage(self):
        from omldm_tpu.ops.native import FusedStage

        if getattr(self, "_fused", None) is None:
            hash_dims = int(
                self.request.training_configuration.extra.get("hashDims", 0)
            )
            self._fused = FusedStage(
                self._stage_x,
                self._stage_y,
                self.test_set._x,
                self.test_set._y,
                n_features=self.dim - hash_dims,
                test_enabled=bool(self.config.test),
            )
        return self._fused

    def ingest_file(
        self, path: str, chunk_bytes: int = 1 << 22, on_chunk=None
    ) -> None:
        """Stream a JSON-lines file through the fused C ingest: every
        fast-schema line is parsed DIRECTLY into its staging slot and
        holdout-split in C (exact handle_batch semantics, pinned by
        tests/test_fused_ingest.py); only stage launches, Python-codec
        fallback lines and forecasts return to Python. This is the e2e
        hot path — one pass, no per-row numpy.

        Reference counterpart: the whole-job per-record hot loop
        Job.scala:42-70 -> FlinkSpoke.scala:92-107."""
        fs = self._fused_stage()
        for buf, stop in _line_aligned_chunks(path, chunk_bytes):
            self._fused_consume(fs, buf, 0, stop)
            if on_chunk is not None:
                on_chunk()

    def supports_overlapped_ingest(self) -> bool:
        """Double-buffered ingest needs chained launches (not SSP's paced
        per-launch accept flags); both the dense fused stage and the
        sparse COO route implement it. It holds ``depth`` extra stage
        buffer sets (default 2: ~3x staging memory); set
        trainingConfiguration extra ``{"overlappedIngest": false}`` to
        keep the serial fused route on memory-tight hosts."""
        flag = str(
            self.request.training_configuration.extra.get(
                "overlappedIngest", "true"
            )
        ).lower()
        return (
            self.supports_fused_ingest() and not self._paced
            and flag != "false"
        )

    def ingest_file_overlapped(
        self, path: str, chunk_bytes: int = 1 << 22, on_chunk=None,
        depth: int = 2, train_fn=None,
    ) -> None:
        """DOUBLE-BUFFERED fused ingest: the C parse/holdout/stage loop
        (which releases the GIL) fills stage buffer k+1 in the calling
        thread while a dispatch thread ships and trains stage k — so the
        measured wall clock of a run is max(parse, device) instead of
        their sum, end to end. ``depth`` spare buffer pairs bound the
        look-ahead (the parse thread blocks on a full queue, so memory
        stays fixed). ``train_fn(sx, sy, n)`` overrides the launch for
        calibrated device-stub measurements.

        Stages are dispatched strictly IN ORDER, so the training result is
        bit-identical to :meth:`ingest_file` (pinned by
        tests/test_overlap.py). Fallback lines and forecasts quiesce the
        dispatch queue first, then run inline — the rare path stays
        correct, the hot path never synchronizes.

        Reference counterpart: the pipelined whole-job hot path
        Job.scala:42-70 -> FlinkSpoke.scala:92-107 (Flink's operator
        chain keeps source/parse and the learner's fit concurrent across
        its task threads; this is the TPU-native two-thread form)."""
        if self._paced:
            raise ValueError(
                "overlapped ingest requires chained launches; SSP's "
                "per-launch accept flags force the serial path"
            )
        from omldm_tpu.ops.native import FusedStage

        hash_dims = int(
            self.request.training_configuration.extra.get("hashDims", 0)
        )

        def make_pair():
            sx = np.zeros_like(self._stage_x)
            sy = np.zeros_like(self._stage_y)
            fs = FusedStage(
                sx, sy, self.test_set._x, self.test_set._y,
                n_features=self.dim - hash_dims,
                test_enabled=bool(self.config.test),
            )
            return (sx, sy, fs)

        train = train_fn or (
            lambda sx, sy, n: self._train_buffer(
                sx, sy, n, full=(n == self._stage_cap)
            )
        )
        disp = _OverlapDispatcher(
            make_pair, depth, lambda s, n: train(s[0], s[1], n)
        )
        current = (self._stage_x, self._stage_y, self._fused_stage())

        def on_stage_full():
            nonlocal current
            current = disp.submit(current, self._stage_cap)
            self._stage_x, self._stage_y = current[0], current[1]
            self._fused = current[2]
            self._stage_n = 0
            return current[2]

        try:
            for buf, stop in _line_aligned_chunks(path, chunk_bytes):
                self._fused_consume(
                    current[2], buf, 0, stop,
                    on_stage_full=on_stage_full, quiesce=disp.quiesce,
                )
                if on_chunk is not None:
                    on_chunk()
            # final partial stage drains through the same ordered queue
            n_tail = self._stage_n
            self._stage_n = 0
            if n_tail:
                disp.submit(current, n_tail)
        finally:
            disp.close()
        disp.raise_pending()

    def _fused_consume(
        self, fs, buf: bytearray, start: int, stop: int,
        on_stage_full=None, quiesce=None,
    ) -> None:
        """Drive the C loop over ``buf[start:stop]`` (whole lines), handing
        stage launches / fallback lines / forecasts back to Python.

        ``on_stage_full`` (double-buffered ingest): called instead of the
        inline stage launch; hands the full buffer to the dispatch thread,
        swaps the parse side to a free buffer pair and returns its
        FusedStage. ``quiesce`` is then called before any branch that
        touches the trainer or the parse-side stage from Python
        (fallback/forecast), so those inline paths never race the
        dispatch thread."""
        ctx = fs.ctx
        off = start
        while off < stop:
            # sync the mutable cursors in (Python code below, and SSP
            # requeue inside _train_staged, may have moved them)
            ctx.stage_n = self._stage_n
            ctx.hold_n = self.test_set._n
            ctx.hold_head = self.test_set._head
            ctx.holdout_count = self.holdout_count
            rc, consumed, soff, slen = fs.parse_stage(buf, off, stop)
            self._stage_n = int(ctx.stage_n)
            self.test_set._n = int(ctx.hold_n)
            self.test_set._head = int(ctx.hold_head)
            self.holdout_count = int(ctx.holdout_count)
            base = off
            off += consumed
            if rc == fs.RC_DONE:
                return
            if rc in (fs.RC_FALLBACK, fs.RC_FORECAST) and quiesce is not None:
                quiesce()
            if rc == fs.RC_STAGE_FULL:
                if on_stage_full is not None:
                    fs = on_stage_full()
                    ctx = fs.ctx
                else:
                    self._train_staged(full=True)
            elif rc == fs.RC_FALLBACK:
                line = bytes(buf[base + soff : base + soff + slen]).decode(
                    "utf-8", errors="replace"
                )
                inst = DataInstance.from_json(line)
                if inst is not None:
                    self.handle_data(inst)
            elif rc == fs.RC_FORECAST:
                x, _ = fs.forecast_row()
                xb = np.zeros((PREDICT_BATCH, self.dim), np.float32)
                xb[0] = x
                preds = self.trainer.predict(xb)
                inst = DataInstance(
                    numerical_features=x.tolist(), operation=FORECASTING
                )
                self._emit_prediction(
                    Prediction(self.request.id, inst, float(preds[0]))
                )

    # --- query / termination path ---

    def _evaluate(self) -> Tuple[float, float]:
        if self.test_set.is_empty:
            return 0.0, 0.0
        if self._resident is not None:
            # serve the eval straight from the resident holdout ring
            xs, ys, mask = self._resident.eval_arrays()
            return self.trainer.evaluate(xs, ys, mask)
        xs, ys = self.test_set.arrays()
        # pad to the holdout capacity so the jitted eval program compiles
        # once, not once per fill level while the holdout warms up
        cap = self.test_set.max_size
        n = len(ys)
        if n < cap:
            pad = cap - n
            xs = np.concatenate([xs, np.zeros((pad, xs.shape[1]), xs.dtype)])
            ys = np.concatenate([ys, np.zeros((pad,), ys.dtype)])
        mask = np.zeros((cap,), np.float32)
        mask[:n] = 1.0
        return self.trainer.evaluate(xs, ys, mask)

    def emit_query_response(self, response_id: int) -> None:
        """Bucketed QueryResponse (FlinkNetwork.scala:48-149,151-240); the
        fleet model is one logical model, so user queries get a single
        worker's fragment set (the merger expects 1)."""
        self.flush()
        loss, score = self._evaluate()
        flat = self.trainer.global_flat_params()
        chunks: List[Optional[np.ndarray]] = [None]
        if response_id != TERMINATION_RESPONSE_ID:
            bucket = self.config.max_param_bucket_size
            chunks = [
                flat[i : i + bucket]
                for i in range(0, max(flat.size, 1), bucket)
            ] or [None]
        tc = self.request.training_configuration
        learner_desc = {
            "name": self.request.learner.name,
            "hyperParameters": dict(self.request.learner.hyper_parameters or {}),
            "dataStructure": dict(self.request.learner.data_structure or {}),
        }
        n_workers = (
            self.config.parallelism
            if response_id == TERMINATION_RESPONSE_ID
            else 1
        )
        fitted = self.trainer.fitted
        for w in range(n_workers):
            for i, chunk in enumerate(chunks):
                learner = (
                    dict(learner_desc) if i == 0
                    else {"name": learner_desc["name"]}
                )
                if chunk is not None:
                    learner["parameters"] = {"bucketValues": chunk.tolist()}
                self._emit_response(
                    QueryResponse(
                        response_id=response_id,
                        mlp_id=self.request.id,
                        bucket=i,
                        num_buckets=len(chunks),
                        preprocessors=[
                            {"name": p.name, "hyperParameters": dict(p.hyper_parameters or {})}
                            for p in (self.request.preprocessors or [])
                        ] if i == 0 else None,
                        learner=learner,
                        protocol=tc.protocol if i == 0 else None,
                        # fitted counts once across the fleet's fragments
                        data_fitted=fitted if (i == 0 and w == 0) else 0,
                        loss=loss if i == 0 else None,
                        cumulative_loss=None,
                        score=score if i == 0 else None,
                        source_worker=w,
                    )
                )

    def handle_terminate_probe(self) -> None:
        self.emit_query_response(TERMINATION_RESPONSE_ID)

    def network_statistics(self) -> Statistics:
        """Protocol statistics with the collective-call-site accounting
        (bytesShipped parity, FlinkHub.scala:118-127)."""
        curve = self.trainer.curve_slice()
        _, score = self._evaluate()
        return Statistics(
            pipeline=self.request.id,
            protocol=self.request.training_configuration.protocol,
            models_shipped=self.trainer.sync_count() * self.dp,
            bytes_shipped=self.trainer.bytes_shipped(),
            bytes_on_wire=self.trainer.bytes_on_wire(),
            num_of_blocks=self.trainer.sync_count(),
            fitted=self.trainer.fitted,
            learning_curve=[l for l, _ in curve],
            lcx=[f for _, f in curve],
            mean_buffer_size=float(self._stage_n),
            score=score,
        )


class SparseSPMDBridge(SPMDBridge):
    """Padded-COO pipeline on the collective engine: the model vector stays
    dense and hub-sharded on the mesh, each record ships only its K active
    features ((idx[K], val[K]) — the SparseVector input type of the
    reference's parse path, DataPointParser.scala:4,20-47), and protocol
    sync is the same XLA collective as the dense bridge. Streaming contract
    identical: 8-of-10 holdout, forecasts at stream position, bucketed
    query responses, termination fragments, byte-accounted statistics."""

    # sparse chunks default to 8 MB (vs the dense 4 MB): the MT parse
    # amortizes its newline-index pass and thread handoff over longer
    # line runs — measured ~+8% host throughput on the Criteo stream
    SPARSE_CHUNK_BYTES = 1 << 23

    def __init__(self, request, dim, config, emit_prediction, emit_response):
        super().__init__(request, dim, config, emit_prediction, emit_response)
        from omldm_tpu.runtime.databuffers import SparseHoldout
        from omldm_tpu.runtime.vectorizer import SparseVectorizer

        ds = request.learner.data_structure or {}
        self.max_nnz = int(ds.get("maxNnz", 64))
        hash_space = int(ds.get("hashSpace", 0))
        self.vectorizer = SparseVectorizer(dim, hash_space, self.max_nnz)
        self.test_set = SparseHoldout(config.test_set_size, self.max_nnz)
        # COO staging: one [dp, B] group per launch (no dense chaining)
        self.chain = 1
        self._stage_cap = self.dp * config.batch_size
        self._stage_i = np.zeros((self._stage_cap, self.max_nnz), np.int32)
        self._stage_v = np.zeros((self._stage_cap, self.max_nnz), np.float32)
        self._stage_y = np.zeros((self._stage_cap,), np.float32)
        self._stage_x = self._stage_v  # base-class size probes only
        self._stage_n = 0

    def supports_fused_ingest(self) -> bool:
        """The sparse bridge has its own C bulk routes (ingest_file below:
        the fused parse->holdout->stage loop, or padded-COO block packing
        with in-C categorical hashing)."""
        from omldm_tpu.ops.native import fast_parser_available

        return fast_parser_available()

    # supports_overlapped_ingest: inherited — supports_fused_ingest is
    # polymorphic and the opt-out knob is shared with the dense route.

    def _use_fused_coo(self) -> bool:
        """The fused C loop (omldm_parse_stage_sparse) is the default file
        route: it parses each line directly into its COO stage slot with
        the holdout split in C, where the block route re-touches every row
        in numpy (parser output allocation, holdout mask/argsort/concat,
        stage memcpy) — ~2x host throughput measured on the Criteo-shaped
        stream (benchmarks/run_benchmarks.py:bench_criteo_sparse_stream_e2e).
        ``{"sparseFusedIngest": false}`` keeps the multithreaded block
        parser instead (it can win on many-core hosts where the e2e is
        parse-bound and the fused loop's single parse thread loses to 8
        MT block threads)."""
        if not self.supports_fused_ingest():
            return False
        flag = str(
            self.request.training_configuration.extra.get(
                "sparseFusedIngest", "true"
            )
        ).lower()
        return flag != "false"

    def _sparse_fused_stage(self):
        from omldm_tpu.ops.native import SparseFusedStage

        if getattr(self, "_fused", None) is None:
            self._fused = SparseFusedStage(
                self._stage_i, self._stage_v, self._stage_y,
                self.test_set._idx, self.test_set._val, self.test_set._y,
                dense_budget=self.vectorizer.dim - self.vectorizer.hash_space,
                hash_space=self.vectorizer.hash_space,
                test_enabled=bool(self.config.test),
            )
        return self._fused

    def _fused_consume_sparse(
        self, fs, buf: bytearray, start: int, stop: int,
        on_stage_full=None, quiesce=None,
    ) -> None:
        """Drive the fused sparse C loop over ``buf[start:stop]`` (whole
        lines), handing stage launches and special lines back to Python —
        the COO twin of the dense :meth:`_fused_consume`, with the same
        cursor-sync contract. Specials (codec fallbacks AND forecasts)
        re-enter via DataInstance.from_json -> handle_data, which is
        byte-identical to the block route's special path; ``quiesce``
        drains the dispatch queue first so the rare path never races the
        dispatch thread on trainer state."""
        ctx = fs.ctx
        off = start
        while off < stop:
            ctx.stage_n = self._stage_n
            ctx.hold_n = self.test_set._n
            ctx.hold_head = self.test_set._head
            ctx.holdout_count = self.holdout_count
            rc, consumed, soff, slen = fs.parse_stage(buf, off, stop)
            self._stage_n = int(ctx.stage_n)
            self.test_set._n = int(ctx.hold_n)
            self.test_set._head = int(ctx.hold_head)
            self.holdout_count = int(ctx.holdout_count)
            base = off
            off += consumed
            if rc == fs.RC_DONE:
                return
            if rc == fs.RC_STAGE_FULL:
                if on_stage_full is not None:
                    fs = on_stage_full()
                    ctx = fs.ctx
                else:
                    self._train_staged(full=True)
            elif rc == fs.RC_SPECIAL:
                if quiesce is not None:
                    quiesce()
                line = bytes(buf[base + soff : base + soff + slen]).decode(
                    "utf-8", errors="replace"
                )
                inst = DataInstance.from_json(line)
                if inst is not None:
                    self.handle_data(inst)

    def _make_coo_parser(self):
        from omldm_tpu.ops.native import SparseFastParser

        # parserThreads: 0 = auto (min(cores, 8), FastParser's rule) —
        # multi-core hosts parse disjoint line ranges on C threads.
        # reuse_buffers: the ingest routes consume every returned array
        # within the chunk (staging memcpy / holdout copy), so the parser
        # may hand out scratch views instead of fresh allocations
        return SparseFastParser(
            self.vectorizer.dim - self.vectorizer.hash_space,
            self.vectorizer.hash_space,
            self.max_nnz,
            n_threads=int(
                self.request.training_configuration.extra.get(
                    "parserThreads", 0
                )
            ),
            reuse_buffers=True,
        )

    def ingest_file_overlapped(
        self, path: str, chunk_bytes: int = SPARSE_CHUNK_BYTES, on_chunk=None,
        depth: int = 2, train_fn=None,
    ) -> None:
        """DOUBLE-BUFFERED COO ingest: the fused C parse -> holdout ->
        stage loop fills stage set k+1 while the dispatch thread runs
        stage k's collective steps — the sparse e2e path is host-parse
        bound and the device scatter costs about as much, so overlapping
        them approaches max() instead of their sum. Stage sets dispatch
        strictly in order: results are bit-identical to the serial
        :meth:`ingest_file` (pinned by tests/test_overlap.py). Specials
        (forecasts, codec fallbacks) quiesce the queue first, exactly
        like the dense route. Hosts opting out of the fused loop
        (``sparseFusedIngest: false``) overlap the MT block route
        instead (:meth:`_ingest_file_overlapped_blocks`)."""
        if self._paced:
            raise ValueError(
                "overlapped ingest requires chained launches; SSP's "
                "per-launch accept flags force the serial path"
            )
        use_fused = self._use_fused_coo()
        parser = self._make_coo_parser() if use_fused else None
        if not use_fused or parser.n_threads > 1:
            # multi-core hosts overlap the MT block parse (all cores in
            # the producer thread, C staging tail) with the dispatch
            # thread; single-core hosts overlap the fused line loop
            self._ingest_file_overlapped_blocks(
                path, chunk_bytes, on_chunk, depth, train_fn, parser
            )
            return
        from omldm_tpu.ops.native import SparseFusedStage

        dense_budget = self.vectorizer.dim - self.vectorizer.hash_space

        def make_set():
            si = np.zeros_like(self._stage_i)
            sv = np.zeros_like(self._stage_v)
            sy = np.zeros_like(self._stage_y)
            fs = SparseFusedStage(
                si, sv, sy,
                self.test_set._idx, self.test_set._val, self.test_set._y,
                dense_budget=dense_budget,
                hash_space=self.vectorizer.hash_space,
                test_enabled=bool(self.config.test),
            )
            return (si, sv, sy, fs)

        train = train_fn or (
            lambda si, sv, sy, n: self._launch_coo(si, sv, sy, n)
        )
        disp = _OverlapDispatcher(
            make_set, depth, lambda s, n: train(s[0], s[1], s[2], n)
        )
        current = (
            self._stage_i, self._stage_v, self._stage_y,
            self._sparse_fused_stage(),
        )

        def on_stage_full():
            nonlocal current
            current = disp.submit(current, self._stage_cap)
            self._stage_i, self._stage_v, self._stage_y = current[:3]
            self._stage_x = self._stage_v  # base-class size probes
            self._fused = current[3]
            self._stage_n = 0
            return current[3]

        try:
            for buf, stop in _line_aligned_chunks(path, chunk_bytes):
                # surface a dispatch-thread error at the next chunk
                # boundary instead of parsing the rest of the file first
                disp.raise_pending()
                self._fused_consume_sparse(
                    current[3], buf, 0, stop,
                    on_stage_full=on_stage_full, quiesce=disp.quiesce,
                )
                if on_chunk is not None:
                    on_chunk()
            # final partial stage drains through the same ordered queue
            n_tail = self._stage_n
            self._stage_n = 0
            if n_tail:
                disp.submit(current, n_tail)
        finally:
            disp.close()
        disp.raise_pending()

    def _ingest_file_overlapped_blocks(
        self, path: str, chunk_bytes: int, on_chunk, depth: int, train_fn,
        parser=None,
    ) -> None:
        """The block-parse overlapped route: MT parse in the producer
        thread, C (fused) or numpy holdout/staging, stage sets through
        the same ordered dispatcher. Also serves ``sparseFusedIngest:
        false`` hosts."""
        if parser is None:
            parser = self._make_coo_parser()

        def make_set():
            return (
                np.zeros_like(self._stage_i),
                np.zeros_like(self._stage_v),
                np.zeros_like(self._stage_y),
            )

        train = train_fn or (
            lambda si, sv, sy, n: self._launch_coo(si, sv, sy, n)
        )
        disp = _OverlapDispatcher(
            make_set, depth, lambda s, n: train(s[0], s[1], s[2], n)
        )
        self._coo_enqueue = disp
        self._coo_quiesce = disp.quiesce
        try:
            for buf, stop in _line_aligned_chunks(path, chunk_bytes):
                disp.raise_pending()
                self._consume_coo_block(parser, buf, stop)
                if on_chunk is not None:
                    on_chunk()
            # final partial stage drains through the same ordered queue
            n_tail = self._stage_n
            self._stage_n = 0
            if n_tail:
                (self._stage_i, self._stage_v, self._stage_y) = disp.submit(
                    (self._stage_i, self._stage_v, self._stage_y), n_tail
                )
                self._stage_x = self._stage_v
                self._fused = None  # C-stager driver follows the swap
        finally:
            self._coo_enqueue = None
            self._coo_quiesce = None
            disp.close()
        disp.raise_pending()

    # --- data path ---

    def handle_data(self, inst: DataInstance) -> None:
        idx, val = self.vectorizer.vectorize(inst)
        if inst.operation == FORECASTING:
            self._emit_forecast(idx, val, inst)
            return
        y = (
            0.0 if inst.target is None
            else min(max(float(inst.target), -F32_MAX), F32_MAX)
        )
        self._holdout_then_stage(
            idx[None, :], val[None, :], np.asarray([y], np.float32)
        )

    def _emit_forecast(self, idx, val, inst: DataInstance) -> None:
        bi = np.zeros((PREDICT_BATCH, self.max_nnz), np.int32)
        bv = np.zeros((PREDICT_BATCH, self.max_nnz), np.float32)
        bi[0] = idx
        bv[0] = val
        preds = self.trainer.predict((bi, bv))
        self._emit_prediction(
            Prediction(self.request.id, inst, float(preds[0]))
        )

    def handle_batch(self, x, y, op) -> None:
        """Dense packed rows (the C ingest path) re-enter as COO — rare for
        sparse jobs (the CLI routes sparse streams per-record), but a mixed
        feed must behave identically to per-record delivery."""
        from omldm_tpu.runtime.spoke import Spoke

        n = x.shape[0]
        if n == 0:
            return
        f_idx = np.nonzero(op != 0)[0]
        prev = 0
        for f in f_idx:
            f = int(f)
            if f > prev:
                si, sv = Spoke._dense_rows_to_coo(x[prev:f], self.max_nnz)
                self._train_sparse_rows(si, sv, y[prev:f])
            si, sv = Spoke._dense_rows_to_coo(x[f : f + 1], self.max_nnz)
            inst = DataInstance(
                numerical_features=x[f].tolist(), operation=FORECASTING
            )
            self._emit_forecast(si[0], sv[0], inst)
            prev = f + 1
        if prev < n:
            si, sv = Spoke._dense_rows_to_coo(x[prev:], self.max_nnz)
            self._train_sparse_rows(si, sv, y[prev:])

    def _train_sparse_rows(self, idx, val, y) -> None:
        y = np.clip(np.asarray(y, np.float64), -F32_MAX, F32_MAX).astype(
            np.float32
        )
        self._holdout_then_stage(idx, val, y)

    def _holdout_then_stage(self, idx, val, y) -> None:
        """8-of-10 holdout cycle with evicted rows re-entering at the
        evicting row's stream position (exact dense-bridge semantics)."""
        n = idx.shape[0]
        if n == 0:
            return
        if self.config.test:
            c = (self.holdout_count + np.arange(n)) % 10
            self.holdout_count += n
            test_mask = c >= 8
            keep = np.nonzero(~test_mask)[0]
            t_idx = np.nonzero(test_mask)[0]
            ev_i, ev_v, ev_y, ev_src = self.test_set.append_many(
                idx[t_idx], val[t_idx], y[t_idx]
            )
            if ev_src.size:
                pos = np.concatenate([keep, t_idx[ev_src]])
                order = np.argsort(pos, kind="stable")
                idx = np.concatenate([idx[keep], ev_i])[order]
                val = np.concatenate([val[keep], ev_v])[order]
                y = np.concatenate([y[keep], ev_y])[order]
            else:
                idx, val, y = idx[keep], val[keep], y[keep]
        else:
            self.holdout_count += n
        self._stage_coo(idx, val, y)

    def _stage_coo(self, idx, val, y) -> None:
        """Fill the COO stage (the sparse twin of _stage_rows); a full
        stage launches one [dp, B] collective step and the fill resumes —
        overflow beyond the stage capacity trains rather than truncating
        (restore under a smaller mesh relies on this)."""
        i = 0
        n = idx.shape[0]
        while i < n:
            take = min(self._stage_cap - self._stage_n, n - i)
            s = self._stage_n
            self._stage_i[s : s + take] = idx[i : i + take]
            self._stage_v[s : s + take] = val[i : i + take]
            self._stage_y[s : s + take] = y[i : i + take]
            self._stage_n += take
            i += take
            if self._stage_n >= self._stage_cap:
                self._train_staged(full=True)

    def _train_staged(self, full: bool = False) -> None:
        n = self._stage_n
        if n == 0:
            return
        # double-buffered ingest: hand the filled stage set to the
        # dispatch thread and continue parsing into a fresh set from the
        # pool (the serial path launches inline below)
        if getattr(self, "_coo_enqueue", None) is not None:
            (self._stage_i, self._stage_v, self._stage_y) = (
                self._coo_enqueue.submit(
                    (self._stage_i, self._stage_v, self._stage_y), n
                )
            )
            self._stage_x = self._stage_v  # base-class size probes
            # the cached C-stager driver points at the buffers that were
            # just handed to the dispatch thread: rebuild over the new set
            self._fused = None
            self._stage_n = 0
            return
        self._stage_n = 0
        self._launch_coo(
            self._stage_i, self._stage_v, self._stage_y, n
        )

    def _launch_coo(self, si, sv, sy, n) -> None:
        """Launch ``n`` staged COO rows (explicit arrays, so the
        double-buffered dispatch thread can drive it on pooled sets).
        Rows are COPIED before device handoff: dispatch is async and jax
        may alias numpy argument buffers zero-copy (observed on CPU),
        while both the serial stage and the pooled sets are reused as
        soon as this returns; SSP requeue also re-enters these buffers."""
        si = si[:n].copy()
        sv = sv[:n].copy()
        sy = sy[:n].copy()
        b = self.config.batch_size
        group = self.dp * b
        done = 0
        while n - done >= group:
            ig = si[done : done + group].reshape(self.dp, b, self.max_nnz)
            vg = sv[done : done + group].reshape(self.dp, b, self.max_nnz)
            yg = sy[done : done + group].reshape(self.dp, b)
            mg = np.ones((self.dp, b), np.float32)
            self.trainer.step((ig, vg), yg, mg, valid_count=group)
            self._requeue_refused_sparse(ig, vg, yg, mg)
            done += group
        tail_b = min(b, TAIL_BATCH)
        tail_group = self.dp * tail_b
        while n - done > 0:
            rem = min(n - done, tail_group)
            ti = np.zeros((tail_group, self.max_nnz), np.int32)
            tv = np.zeros((tail_group, self.max_nnz), np.float32)
            ty = np.zeros((tail_group,), np.float32)
            tm = np.zeros((tail_group,), np.float32)
            ti[:rem] = si[done : done + rem]
            tv[:rem] = sv[done : done + rem]
            ty[:rem] = sy[done : done + rem]
            tm[:rem] = 1.0
            # stripe rows across workers; SSP maps slots slowest-first so
            # every tail pass is guaranteed progress (dense-bridge rule)
            ig = np.ascontiguousarray(
                ti.reshape(tail_b, self.dp, self.max_nnz).transpose(1, 0, 2)
            )
            vg = np.ascontiguousarray(
                tv.reshape(tail_b, self.dp, self.max_nnz).transpose(1, 0, 2)
            )
            yg = np.ascontiguousarray(ty.reshape(tail_b, self.dp).T)
            mg = np.ascontiguousarray(tm.reshape(tail_b, self.dp).T)
            if self._paced:
                order = np.argsort(self.trainer.worker_clocks(), kind="stable")
                inv = np.empty_like(order)
                inv[order] = np.arange(self.dp)
                ig, vg, yg, mg = ig[inv], vg[inv], yg[inv], mg[inv]
            self.trainer.step((ig, vg), yg, mg, valid_count=rem)
            self._requeue_refused_sparse(ig, vg, yg, mg)
            done += rem

    def _requeue_refused_sparse(self, ig, vg, yg, mg) -> None:
        if not self._paced:
            return
        acc = self.trainer.last_accepted()
        if acc.all():
            return
        for w in np.nonzero(~acc)[0]:
            rows = mg[w] > 0.0
            k = int(rows.sum())
            if k == 0:
                continue
            self.trainer.note_requeued(k)
            # refused rows re-enter the stage directly (they already went
            # through the holdout cycle)
            self._stage_coo(ig[w][rows], vg[w][rows], yg[w][rows])

    # --- evaluation / checkpoint buffers ---

    def _evaluate(self):
        if self.test_set.is_empty:
            return 0.0, 0.0
        ti, tv, ty = self.test_set.arrays()
        cap = self.test_set.max_size
        n = len(ty)
        if n < cap:
            pad = cap - n
            ti = np.concatenate(
                [ti, np.zeros((pad, self.max_nnz), np.int32)]
            )
            tv = np.concatenate(
                [tv, np.zeros((pad, self.max_nnz), np.float32)]
            )
            ty = np.concatenate([ty, np.zeros((pad,), np.float32)])
        mask = np.zeros((cap,), np.float32)
        mask[:n] = 1.0
        return self.trainer.evaluate((ti, tv), ty, mask)

    def snapshot_buffers(self) -> dict:
        ti, tv, ty = self.test_set.arrays()
        return {
            "sparse": True,
            "test_i": ti.copy(),
            "test_v": tv.copy(),
            "test_yv": ty.copy(),
            "stage_i": self._stage_i[: self._stage_n].copy(),
            "stage_v": self._stage_v[: self._stage_n].copy(),
            "stage_yv": self._stage_y[: self._stage_n].copy(),
            # dense-keyed empties keep old readers from crashing
            "test_x": np.zeros((0, 1), np.float32),
            "test_y": np.zeros((0,), np.float32),
            "stage_x": np.zeros((0, 1), np.float32),
            "stage_y": np.zeros((0,), np.float32),
        }

    def restore_buffers(self, bd: dict) -> None:
        if bd.get("test_i") is not None and bd["test_i"].shape[0]:
            self.test_set.append_many(
                bd["test_i"], bd["test_v"], bd["test_yv"]
            )
        if bd.get("stage_i") is not None and bd["stage_i"].shape[0]:
            # through the stage filler: a snapshot taken on a larger mesh
            # may carry more staged rows than this bridge's capacity, and
            # the overflow must train, not crash or truncate
            self._stage_coo(bd["stage_i"], bd["stage_v"], bd["stage_yv"])

    # --- bulk file ingest via the C sparse parser ---

    def ingest_file(
        self, path: str, chunk_bytes: int = SPARSE_CHUNK_BYTES, on_chunk=None
    ) -> None:
        """Stream a JSON-lines file through the fused sparse C loop:
        every fast-schema line is parsed DIRECTLY into its COO stage slot
        (zlib-CRC32 categorical hashing in C, parity fuzz-pinned by
        tests/test_sparse_parser.py) and holdout-split in C — the sparse
        twin of the dense fused route, bit-identical to the block route
        (pinned by tests/test_sparse_spmd_bridge.py). Fallback lines,
        forecasts and drops re-route through the per-record codec at
        their stream position; ``sparseFusedIngest: false`` keeps the MT
        block route."""
        if self._use_fused_coo():
            parser = self._make_coo_parser()
            if parser.n_threads <= 1:
                # single-core host: the fused line loop (one C pass,
                # parse straight into the stage slot) beats any split
                for buf, stop in _line_aligned_chunks(path, chunk_bytes):
                    self._fused_consume_sparse(
                        self._sparse_fused_stage(), buf, 0, stop
                    )
                    if on_chunk is not None:
                        on_chunk()
                return
            # multi-core host: MT block parse on all cores, then the C
            # stager (_consume_coo_block routes staging through
            # omldm_stage_coo_rows when the fused path is enabled)
        else:
            parser = self._make_coo_parser()
        for buf, stop in _line_aligned_chunks(path, chunk_bytes):
            self._consume_coo_block(parser, buf, stop)
            if on_chunk is not None:
                on_chunk()

    def _consume_coo_block(self, parser, buf, stop: int = None) -> None:
        """MT block parse of ``buf[:stop]`` (zero-copy out of the reusable
        read buffer) + vectorized holdout/staging. ``buf`` may also be a
        plain bytes block (Kafka feeds), in which case ``stop`` defaults
        to its length."""
        if stop is None:
            stop = len(buf)
        if isinstance(buf, (bytes, memoryview)):
            block = bytes(buf[:stop])
            idx, val, y, op, valid = parser.parse(block)
        else:
            block = None  # materialized lazily, only for special lines
            idx, val, y, op, valid = parser.parse_range(buf, 0, stop)
        n = idx.shape[0]
        if n == 0:
            return
        # specials (codec fallbacks, forecasts, drops) break the bulk run
        # so ordering matches per-record delivery exactly
        special = np.nonzero((valid != 1) | (op != 0))[0]
        lines = None
        if special.size:
            if block is None:
                block = bytes(memoryview(buf)[:stop])
            lines = block.split(b"\n")
        # bulk runs of parsed training rows: holdout + stage in C when the
        # fused path is on (same per-record semantics either way)
        stage_bulk = (
            self._stage_parsed_rows if self._use_fused_coo()
            else self._train_sparse_rows
        )
        prev = 0
        for s in special:
            s = int(s)
            if s > prev:
                stage_bulk(idx[prev:s], val[prev:s], y[prev:s])
            inst = DataInstance.from_json(
                lines[s].decode("utf-8", errors="replace")
            )
            if inst is not None:
                if getattr(self, "_coo_quiesce", None) is not None:
                    # specials may touch the trainer from this (producer)
                    # thread (forecasts serve a prediction): drain queued
                    # collective steps first — including any enqueued by
                    # the staging right above — so two threads never race
                    # on trainer state
                    self._coo_quiesce()
                self.handle_data(inst)
            prev = s + 1
        if prev < n:
            stage_bulk(idx[prev:], val[prev:], y[prev:])

    def _stage_parsed_rows(self, idx, val, y) -> None:
        """Holdout + stage a run of C-PARSED COO rows through the C stager
        (omldm_stage_coo_rows): the staging tail of the MT block route,
        bit-identical to :meth:`_holdout_then_stage` + :meth:`_stage_coo`
        but with the holdout cycle, ring swap and stage fill in one C pass
        instead of mask/argsort/concatenate numpy per block. Pauses at
        stage-full for the launch (or the overlapped dispatch swap)."""
        n = idx.shape[0]
        i = 0
        while i < n:
            # re-fetch per pass: a stage swap (overlapped dispatch)
            # invalidates the cached driver
            fs = self._sparse_fused_stage()
            ctx = fs.ctx
            ctx.stage_n = self._stage_n
            ctx.hold_n = self.test_set._n
            ctx.hold_head = self.test_set._head
            ctx.holdout_count = self.holdout_count
            took = fs.stage_rows(idx, val, y, i)
            self._stage_n = int(ctx.stage_n)
            self.test_set._n = int(ctx.hold_n)
            self.test_set._head = int(ctx.hold_head)
            self.holdout_count = int(ctx.holdout_count)
            i += took
            if self._stage_n >= self._stage_cap:
                self._train_staged(full=True)
