"""SPMDBridge: host one streaming pipeline on the collective SPMD engine.

The streaming runtime's host plane multiplexes pipelines across in-process
spokes (message-passing protocol sync, SURVEY.md §3.3); this bridge is the
second deployment mode: a pipeline whose ``trainingConfiguration`` sets
``{"engine": "spmd"}`` trains on :class:`omldm_tpu.parallel.SPMDTrainer`
instead — every data-parallel worker is a mesh shard and protocol sync is
an XLA collective over ICI, while the pipeline keeps the EXACT streaming
contract of a host-plane pipeline: 8-of-10 holdout sampling, micro-batch
training of evicted/kept records, forecasting predictions, bucketed query
responses, the responseId -1 termination fragments (one per configured
worker so the parallelism x pipelines countdown is preserved,
StatisticsOperator.scala:109), and protocol statistics with
bytesShipped/modelsShipped accounting from the collective call sites.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

from omldm_tpu.api.data import FORECASTING, DataInstance, Prediction
from omldm_tpu.api.requests import Request
from omldm_tpu.api.responses import TERMINATION_RESPONSE_ID, QueryResponse
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.parallel.mesh import make_mesh
from omldm_tpu.parallel.spmd import SPMD_PROTOCOLS, SPMDTrainer
from omldm_tpu.runtime.databuffers import DataSet
from omldm_tpu.runtime.spoke import PREDICT_BATCH
from omldm_tpu.runtime.vectorizer import Vectorizer


def spmd_engine_requested(request: Request) -> bool:
    return (
        str(request.training_configuration.extra.get("engine", "")).lower()
        == "spmd"
    )


def spmd_engine_supported(request: Request) -> bool:
    """The engine hosts the 6 collective protocols with device learners;
    anything else falls back to the host plane."""
    protocol = request.training_configuration.protocol
    learner = request.learner.name if request.learner else ""
    return protocol in SPMD_PROTOCOLS and learner not in ("HT",)


class SPMDBridge:
    """One pipeline, streaming in, trained across the device mesh."""

    def __init__(
        self,
        request: Request,
        dim: int,
        config: JobConfig,
        emit_prediction: Callable[[Prediction], None],
        emit_response: Callable[[QueryResponse], None],
    ):
        self.request = request
        self.config = config
        self._emit_prediction = emit_prediction
        self._emit_response = emit_response
        tc = request.training_configuration
        n_dev = len(jax.devices())
        hub = max(int(tc.hub_parallelism), 1)
        if hub > n_dev:
            hub = 1
        # as many mesh workers as devices allow, capped by the job's
        # configured parallelism (the virtual worker count for statistics)
        dp = max(min(config.parallelism, n_dev // hub), 1)
        self.trainer = SPMDTrainer(
            request.learner,
            request.preprocessors or (),
            dim=dim,
            protocol=tc.protocol,
            mesh=make_mesh(dp=dp, hub=hub),
            training_configuration=tc,
            batch_size=config.batch_size,
        )
        self.dp = dp
        hash_dims = int(tc.extra.get("hashDims", 0))
        self.vectorizer = Vectorizer(dim, hash_dims)
        self.dim = dim
        self.test_set: DataSet[Tuple[np.ndarray, float]] = DataSet(
            config.test_set_size
        )
        self.holdout_count = 0
        # staged rows round-robined across the dp worker slots
        self._rows_x: List[np.ndarray] = []
        self._rows_y: List[float] = []

    # --- data path ---

    def handle_data(self, inst: DataInstance) -> None:
        x = self.vectorizer.vectorize(inst)
        if inst.operation == FORECASTING:
            xb = np.zeros((PREDICT_BATCH, self.dim), np.float32)
            xb[0] = x
            preds = self.trainer.predict(xb)
            self._emit_prediction(
                Prediction(self.request.id, inst, float(preds[0]))
            )
            return
        y = 0.0 if inst.target is None else float(inst.target)
        # 20% holdout: counts 8,9 of each 0-9 cycle (FlinkSpoke.scala:94-104)
        c = self.holdout_count % 10
        self.holdout_count += 1
        if self.config.test and c >= 8:
            evicted = self.test_set.append((x, y))
            if evicted is None:
                return
            x, y = evicted
        self._rows_x.append(x)
        self._rows_y.append(y)
        if len(self._rows_x) >= self.dp * self.config.batch_size:
            self._train_staged()

    def _train_staged(self) -> None:
        """Train the staged rows as one [dp, B, D] fleet step (padded with
        a zero mask when the stage is partial)."""
        n = len(self._rows_x)
        if n == 0:
            return
        b = self.config.batch_size
        total = self.dp * b
        x = np.zeros((total, self.dim), np.float32)
        y = np.zeros((total,), np.float32)
        mask = np.zeros((total,), np.float32)
        x[:n] = np.stack(self._rows_x)
        y[:n] = np.asarray(self._rows_y, np.float32)
        mask[:n] = 1.0
        self._rows_x, self._rows_y = [], []
        self.trainer.step(
            x.reshape(self.dp, b, self.dim),
            y.reshape(self.dp, b),
            mask.reshape(self.dp, b),
            valid_count=n,
        )

    def flush(self) -> None:
        self._train_staged()

    # --- query / termination path ---

    def _evaluate(self) -> Tuple[float, float]:
        if self.test_set.is_empty:
            return 0.0, 0.0
        xs = np.stack([p[0] for p in self.test_set])
        ys = np.asarray([p[1] for p in self.test_set], np.float32)
        return self.trainer.evaluate(xs, ys, np.ones(len(ys), np.float32))

    def emit_query_response(self, response_id: int) -> None:
        """Bucketed QueryResponse (FlinkNetwork.scala:48-149,151-240); the
        fleet model is one logical model, so user queries get a single
        worker's fragment set (the merger expects 1)."""
        self.flush()
        loss, score = self._evaluate()
        flat = self.trainer.global_flat_params()
        chunks: List[Optional[np.ndarray]] = [None]
        if response_id != TERMINATION_RESPONSE_ID:
            bucket = self.config.max_param_bucket_size
            chunks = [
                flat[i : i + bucket]
                for i in range(0, max(flat.size, 1), bucket)
            ] or [None]
        tc = self.request.training_configuration
        learner_desc = {
            "name": self.request.learner.name,
            "hyperParameters": dict(self.request.learner.hyper_parameters or {}),
            "dataStructure": dict(self.request.learner.data_structure or {}),
        }
        n_workers = (
            self.config.parallelism
            if response_id == TERMINATION_RESPONSE_ID
            else 1
        )
        fitted = self.trainer.fitted
        for w in range(n_workers):
            for i, chunk in enumerate(chunks):
                learner = (
                    dict(learner_desc) if i == 0
                    else {"name": learner_desc["name"]}
                )
                if chunk is not None:
                    learner["parameters"] = {"bucketValues": chunk.tolist()}
                self._emit_response(
                    QueryResponse(
                        response_id=response_id,
                        mlp_id=self.request.id,
                        bucket=i,
                        num_buckets=len(chunks),
                        preprocessors=[
                            {"name": p.name, "hyperParameters": dict(p.hyper_parameters or {})}
                            for p in (self.request.preprocessors or [])
                        ] if i == 0 else None,
                        learner=learner,
                        protocol=tc.protocol if i == 0 else None,
                        # fitted counts once across the fleet's fragments
                        data_fitted=fitted if (i == 0 and w == 0) else 0,
                        loss=loss if i == 0 else None,
                        cumulative_loss=None,
                        score=score if i == 0 else None,
                        source_worker=w,
                    )
                )

    def handle_terminate_probe(self) -> None:
        self.emit_query_response(TERMINATION_RESPONSE_ID)

    def network_statistics(self) -> Statistics:
        """Protocol statistics with the collective-call-site accounting
        (bytesShipped parity, FlinkHub.scala:118-127)."""
        curve = self.trainer.curve_slice()
        _, score = self._evaluate()
        return Statistics(
            pipeline=self.request.id,
            protocol=self.request.training_configuration.protocol,
            models_shipped=self.trainer.sync_count() * self.dp,
            bytes_shipped=self.trainer.bytes_shipped(),
            num_of_blocks=self.trainer.sync_count(),
            fitted=self.trainer.fitted,
            learning_curve=[l for l, _ in curve],
            lcx=[f for _, f in curve],
            mean_buffer_size=float(len(self._rows_x)),
            score=score,
        )
