"""Record featurization and fixed-shape micro-batch assembly.

Reference counterpart: ``DataPointParser`` turning ``DataInstance`` into
``TrainingPoint``/``ForecastingPoint`` with numerical/discrete/categorical
vectors (DataPointParser.scala:16-54). The reference keeps per-record objects;
the TPU runtime instead assembles fixed-shape padded micro-batches so the
jitted step never recompiles (SURVEY.md section 7 hard part (d)).

Categorical (string) features are feature-hashed into ``hash_dims`` buckets
host-side — the TPU-native equivalent of the reference's categorical encoding
(and the "hashed features" of BASELINE.md config 5).
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Optional, Tuple

import numpy as np

from omldm_tpu.api.data import DataInstance

# float32 boundary clamp: JSON numbers are doubles, and a finite double
# beyond float32 range would otherwise overflow to inf during batch
# assembly (RuntimeWarning host-side, inf-poisoned params device-side).
# The native parser applies the IDENTICAL clamp (fastparse.cpp) so the two
# ingest paths stay bit-equal — pinned by tests/test_parser_fuzz.py.
F32_MAX = float(np.finfo(np.float32).max)


def clamp_f32(feats) -> np.ndarray:
    """float64 view -> clamp to float32 finite range -> float32."""
    a = np.asarray(feats, np.float64)
    return np.clip(a, -F32_MAX, F32_MAX).astype(np.float32)


@dataclasses.dataclass
class Vectorizer:
    """Maps DataInstances to fixed-dim float32 vectors.

    ``dim`` is the total feature width the pipeline was created with; records
    with fewer features are zero-padded, longer ones truncated (the runtime
    boundary enforcing what the kernel layer asserts via shape errors).
    ``hash_dims`` > 0 reserves that many trailing dims for hashed categorical
    features."""

    dim: int
    hash_dims: int = 0

    def vectorize(self, inst: DataInstance) -> np.ndarray:
        out = np.zeros((self.dim,), np.float32)
        pos = 0
        dense_budget = self.dim - self.hash_dims
        for feats in (inst.numerical_features, inst.discrete_features):
            if feats:
                take = min(len(feats), dense_budget - pos)
                if take > 0:
                    out[pos : pos + take] = clamp_f32(feats[:take])
                    pos += take
        if self.hash_dims > 0 and inst.categorical_features:
            base = self.dim - self.hash_dims
            for i, cat in enumerate(inst.categorical_features):
                # stable hash: Python's builtin hash() is salted per process,
                # which would scramble buckets across checkpoint/restore
                h = zlib.crc32(f"{i}={cat}".encode())
                idx = base + (h % self.hash_dims)
                # signed hashing keeps the estimate unbiased
                out[idx] += 1.0 if (h >> 1) % 2 == 0 else -1.0
        return out

    @staticmethod
    def infer_dim(inst: DataInstance, hash_dims: int = 0) -> int:
        """Feature width implied by the first record of a stream."""
        n = len(inst.numerical_features or []) + len(inst.discrete_features or [])
        return n + hash_dims


@dataclasses.dataclass
class SparseVectorizer:
    """Maps DataInstances to padded-COO (idx[K], val[K]) records — the
    TPU-native SparseVector (DataPointParser.scala:4,20-47): dense features
    keep their positional slots [0, dense_dim), categorical features hash
    into [dense_dim, dense_dim + hash_space) WITHOUT densifying. ``dim`` =
    dense_dim + hash_space is the model width; ``max_nnz`` (K) is the fixed
    per-record active-feature budget (pad slots idx=0/val=0 are inert in
    the gather/scatter kernels, ops/sparse.py)."""

    dim: int
    hash_space: int
    max_nnz: int

    def vectorize(self, inst: DataInstance) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.zeros((self.max_nnz,), np.int32)
        val = np.zeros((self.max_nnz,), np.float32)
        k = 0
        pos = 0
        dense_budget = self.dim - self.hash_space
        for feats in (inst.numerical_features, inst.discrete_features):
            if feats:
                for v in feats:
                    if pos >= dense_budget or k >= self.max_nnz:
                        break
                    fv = min(max(float(v), -F32_MAX), F32_MAX)
                    if fv != 0.0:
                        idx[k] = pos
                        val[k] = fv
                        k += 1
                    pos += 1
        if self.hash_space > 0 and inst.categorical_features:
            base = self.dim - self.hash_space
            for i, cat in enumerate(inst.categorical_features):
                if k >= self.max_nnz:
                    break
                h = zlib.crc32(f"{i}={cat}".encode())
                idx[k] = base + (h % self.hash_space)
                # signed hashing keeps the estimate unbiased (same rule as
                # the dense Vectorizer, so dense/sparse models agree)
                val[k] = 1.0 if (h >> 1) % 2 == 0 else -1.0
                k += 1
        return idx, val


class SparseMicroBatcher:
    """Accumulates sparse records into fixed-shape ((idx, val), y, mask)
    micro-batches — the padded-COO twin of MicroBatcher."""

    def __init__(self, max_nnz: int, batch_size: int):
        self.batch_size = batch_size
        self.max_nnz = max_nnz
        self._idx = np.zeros((batch_size, max_nnz), np.int32)
        self._val = np.zeros((batch_size, max_nnz), np.float32)
        self._y = np.zeros((batch_size,), np.float32)
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def queued(self) -> int:
        """Pending (staged, unflushed) rows — the uniform queue-depth
        accessor (same contract as ServingPlane.queued())."""
        return self._n

    @property
    def full(self) -> bool:
        return self._n >= self.batch_size

    def add(self, idx: np.ndarray, val: np.ndarray, y: float) -> None:
        self._idx[self._n] = idx
        self._val[self._n] = val
        self._y[self._n] = y
        self._n += 1

    def flush(self):
        """((idx, val), y, mask) padded batch, or None if empty."""
        if self._n == 0:
            return None
        mask = np.zeros((self.batch_size,), np.float32)
        mask[: self._n] = 1.0
        out = (
            (self._idx.copy(), self._val.copy()),
            self._y.copy(),
            mask,
        )
        self._idx[:] = 0
        self._val[:] = 0.0
        self._y[:] = 0.0
        self._n = 0
        return out

    def drain(self):
        """UNPADDED pending rows ((idx, val), y) and reset; None if empty."""
        if self._n == 0:
            return None
        out = (
            (self._idx[: self._n].copy(), self._val[: self._n].copy()),
            self._y[: self._n].copy(),
        )
        self._idx[:] = 0
        self._val[:] = 0.0
        self._y[:] = 0.0
        self._n = 0
        return out


class MicroBatcher:
    """Accumulates vectorized records into fixed-shape (x, y, mask) batches.

    ``flush`` pads the ragged tail with zero rows and a zero mask — masked
    rows contribute nothing to learner updates (see learners.base)."""

    def __init__(self, dim: int, batch_size: int):
        self.batch_size = batch_size
        self._x = np.zeros((batch_size, dim), np.float32)
        self._y = np.zeros((batch_size,), np.float32)
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def queued(self) -> int:
        """Pending (staged, unflushed) rows — the uniform queue-depth
        accessor (same contract as ServingPlane.queued())."""
        return self._n

    @property
    def full(self) -> bool:
        return self._n >= self.batch_size

    def add(self, x: np.ndarray, y: float) -> None:
        self._x[self._n] = x
        self._y[self._n] = y
        self._n += 1

    def add_many(self, x: np.ndarray, y: np.ndarray) -> int:
        """Bulk-add up to the remaining capacity; returns #rows taken.
        Callers loop: take, flush when full, repeat with the rest."""
        take = min(self.batch_size - self._n, x.shape[0])
        if take > 0:
            self._x[self._n : self._n + take] = x[:take]
            self._y[self._n : self._n + take] = y[:take]
            self._n += take
        return take

    def drain(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Return the UNPADDED pending rows (x[:n], y[:n]) and reset; None
        if empty. Used by rescale merges, which re-feed the rows into
        another batcher rather than training a padded batch."""
        if self._n == 0:
            return None
        out = self._x[: self._n].copy(), self._y[: self._n].copy()
        self._n = 0
        return out

    def flush(self) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Return the padded (x, y, mask) batch and reset; None if empty."""
        if self._n == 0:
            return None
        mask = np.zeros((self.batch_size,), np.float32)
        mask[: self._n] = 1.0
        x = self._x.copy()
        y = self._y.copy()
        x[self._n :] = 0.0
        y[self._n :] = 0.0
        self._n = 0
        return x, y, mask

    def clone_pending_from(self, other: "MicroBatcher") -> None:
        """Adopt another batcher's pending rows — shared-ingest cohort
        members re-sync to the leader batcher's state at segment end."""
        n = other._n
        self._x[:n] = other._x[:n]
        self._y[:n] = other._y[:n]
        self._n = n

    def flush_views(self) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Zero-copy flush: padded VIEWS of the internal buffers (valid only
        until the next add) + a fresh mask. For consumers that copy the
        rows synchronously — the cohort staging path writes them straight
        into its gang buffers, skipping one [B, D] copy per flush."""
        if self._n == 0:
            return None
        mask = np.zeros((self.batch_size,), np.float32)
        mask[: self._n] = 1.0
        self._x[self._n :] = 0.0
        self._y[self._n :] = 0.0
        self._n = 0
        return self._x, self._y, mask
