"""Host-side stream runtime: the TPU-native replacement for the reference's
Flink operator graph (SURVEY.md section 1 layers L1/L2/L5)."""

from omldm_tpu.runtime.job import StreamJob

__all__ = ["StreamJob"]
