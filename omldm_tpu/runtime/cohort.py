"""Cohort execution engine: multi-pipeline co-hosting with gang dispatch.

The host plane hosts one ``MLPipeline`` per (spoke, networkId). PRs 2-5 made
the *single*-pipeline path fast, but with M live pipelines the spoke still
pays M separate tiny XLA program launches per micro-batch cycle:
``_JIT_CACHE`` (pipelines/pipeline.py) shares *compilation* across same-spec
pipelines while *dispatch* stays per-pipeline, so multi-tenant throughput
collapses roughly linearly with pipeline count.

This module groups live pipelines into **cohorts** keyed by the same
``_JIT_CACHE`` key (learner spec, prep chain, dim, per_record), stacks their
state pytrees along a leading pipeline axis, and runs fit / predict /
flat-params for the whole cohort as ONE jitted, donated program launch:

- **Staged gang fit** — ``MLPipeline.fit`` on an attached pipeline *stages*
  its micro-batch instead of dispatching; the spoke's gang barrier (end of a
  record / packed block) launches every staged batch of the cohort as one
  program over ``[capacity, T, B, ...]`` inputs. Capacity and the staging
  depth T are bucketed to powers of two so Create/Update/Delete/rescale
  churn compacts slots (free-list reuse) instead of recompiling; inactive
  slots ride along with zero masks and bit-identically keep their state.
- **Gang member iteration** — the per-member program is ``lax.scan`` of the
  SAME ``fit_impl`` the per-pipeline path jits, iterated over members with
  ``lax.map`` (default on CPU): one launch, and the math per member is
  bit-identical to per-pipeline execution (pinned by tests/test_cohort.py).
  ``cohort_impl="vmap"`` swaps in ``jax.vmap`` — faster on batch-parallel
  backends but subject to batched-reduction rounding (~1e-9 relative), so it
  is only the default off-CPU.
- **Gang flat params** — protocol sync points read/write flat parameter
  vectors (``get_flat_params``/``set_flat_params``). A cohort computes the
  whole ``[capacity, P]`` flat matrix in one launch (cached, row-invalidated
  on writes) and scatters written rows back in one batched unravel+scatter,
  so M same-spec sync points cost O(1) launches instead of O(M) ravels.
- **Deferred protocol actions** — ``WorkerNode`` sync points that would
  force a mid-gang launch (get_flat after the round's fit) register through
  ``MLPipeline.defer_after_launch`` and run right after the gang launch, so
  a sync round stays ONE launch for the whole cohort.
- **Gang hub averaging** — :class:`GangAverager` lets same-protocol cohort
  members' parameter-server shards stage their completed round matrices and
  average them in one stacked ``[M, W, P]`` numpy reduction at the job's
  event barrier (wired to ``SynchronousParameterServer``).

The engine is armed by ``JobConfig.cohort``: ``"off"`` (every route is the
exact pre-cohort code path), ``"auto"`` (cohorts form once
``cohort_min`` homogeneous pipelines are live on a spoke — the default), or
``"on"`` (every eligible pipeline cohorts immediately, capacity 1 up).

**Device sharding** (``JobConfig.cohort_shards``): the tenant axis is
embarrassingly parallel, so with S > 1 shards the cohort lays its leading
pipeline axis across the first S local devices as a ``"tenants"`` mesh axis
(``shard_map`` through the ``utils.jaxcompat`` shim — the same portability
layer the SPMD engine rides) and every gang program — fit, shared-input
fit, gang predict (forecast serving flushes), flat params, and the guard's
fused health vector — runs as ONE sharded launch with the per-shard member
iteration unchanged (``lax.map``/``vmap`` over the shard's local block).
Because members are independent, the per-member math is the SAME program
the single-device cohort runs: shard count 1 is the exact pre-sharding
code path, and sharded execution is bit-identical to it on CPU (pinned by
tests/test_cohort_sharded.py). Slots map to shards in contiguous blocks
(slot s lives on shard ``s // (capacity // S)``), capacity stays a
multiple of S (initial capacity S, doubling growth), Create/Update/Delete
churn compacts into the least-loaded shard's lowest free slot (no shape
change => no recompile, and tenants stay balanced across the mesh), and
the staging buffers transfer per-shard — each device receives its own
contiguous block slice instead of the whole gang input funneling through
one device.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from omldm_tpu.guard import gang_health_values
from omldm_tpu.pipelines.pipeline import (
    _LRU_CAP,
    _LRUCache,
    _build_impls,
    _param_health,
)
from omldm_tpu.utils.jaxcompat import shard_map as _shard_map

# staged batches per member before a launch is forced: bounds the gang input
# tensor [capacity, T, B, D] when a pipeline has no sync point for a while
MAX_STAGE_DEPTH = 32

# gang program cache: (pipeline cache key, use_vmap, n_shards) -> jitted
# callables. Shape specialization inside jit handles the (capacity, T)
# buckets; this cache only bounds the number of traced python callables,
# like _JIT_CACHE.
_GANG_CACHE: _LRUCache = _LRUCache(_LRU_CAP)

# one Mesh per shard count, shared by every cohort at that width (the
# cached gang programs close over it, so cohorts built later must see the
# SAME mesh object their cached programs were traced against)
_MESHES: Dict[int, Any] = {}


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def resolve_cohort_shards(config) -> int:
    """The effective tenant-axis shard count for ``config.cohort_shards``:
    ``off``/empty/<=1 -> 1 (single-device gang launches, the exact
    pre-sharding path), ``auto`` -> the largest power of two <= the local
    device count, an integer -> clamped to the local device count and
    floored to a power of two (capacity buckets double from S, so a pow2
    S keeps them pow2)."""
    spec = str(getattr(config, "cohort_shards", "off") or "off").strip().lower()
    if spec in ("off", "none", "false", "0", "1", ""):
        return 1
    n_dev = len(jax.local_devices())
    if spec == "auto":
        want = n_dev
    else:
        try:
            want = int(spec)
        except ValueError:
            # unrecognized spelling: degrade to single-device like the
            # sibling cohort/cohort_impl knobs, never kill the job
            return 1
    want = min(max(want, 1), n_dev)
    n = 1
    while n * 2 <= want:
        n *= 2
    return n


def _mesh_for(n_shards: int):
    mesh = _MESHES.get(n_shards)
    if mesh is None:
        devices = np.array(jax.local_devices()[:n_shards])
        mesh = jax.sharding.Mesh(devices, ("tenants",))
        _MESHES[n_shards] = mesh
    return mesh


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _build_gang_programs(
    learner, preps, per_record: bool, use_vmap: bool, guarded: bool = False,
    mesh=None,
):
    """The (fit, shared-input fit, predict, flat) jitted programs for a
    cohort spec.

    The member computation is the SAME ``fit_impl`` the per-pipeline path
    jits; only the iteration over members differs (lax.map or vmap).
    ``guarded`` cohorts additionally reduce each member's post-scan
    parameter health (isfinite + squared norm) inside the SAME launch —
    the per-member half of the model-integrity guard, detecting one
    diverging member without extra dispatches or perturbing siblings.

    With ``mesh`` set (device-sharded cohorts), every program wraps in
    ``shard_map`` over the ``tenants`` axis before jit: each shard runs
    the per-member iteration over ITS contiguous block of the leading
    pipeline axis — members are independent, so no collective is needed
    and the per-member math is bitwise the single-device program's. The
    shared-input twin keeps its batches replicated (shipped once) and
    broadcasts per shard; everything else shards on the leading axis."""
    fit_impl, predict_impl, _eval_impl, _ = _build_impls(
        learner, preps, per_record
    )

    def member_fit(st, xs_m, ys_m, ms_m):
        def step(st, batch):
            x, y, m = batch
            new_st, loss = fit_impl(st, x, y, m)
            # zero-mask steps (T padding, inactive slots) keep their state
            # BITWISE: the computed branch is discarded by the select, so
            # even a NaN from an all-masked update cannot leak
            keep = jnp.sum(m) > 0
            new_st = _tree_map(
                lambda a, b: jnp.where(keep, a, b), new_st, st
            )
            return new_st, loss

        st2, losses = jax.lax.scan(step, st, (xs_m, ys_m, ms_m))
        if guarded:
            return st2, (losses, _param_health(st2["params"]))
        return st2, losses

    def _ravel(p):
        return jax.flatten_util.ravel_pytree(p)[0]

    if use_vmap:
        gang_fit = jax.vmap(member_fit)
        gang_predict = jax.vmap(predict_impl)
        gang_flat = jax.vmap(_ravel)
    else:
        def gang_fit(state, xs, ys, ms):
            return jax.lax.map(
                lambda z: member_fit(*z), (state, xs, ys, ms)
            )

        def gang_predict(state, xs):
            return jax.lax.map(lambda z: predict_impl(*z), (state, xs))

        def gang_flat(params):
            return jax.lax.map(_ravel, params)

    def gang_fit_shared(state, active, xs, ys, ms):
        # SHARED-input twin: every member trains the same [T, B, ...]
        # batches, shipped ONCE and broadcast in-program (XLA folds the
        # broadcast into the per-member slices, so the host->device
        # conversion stops scaling with the member count). The member
        # computation is gang_fit's own — inactive slots just see zero
        # masks, the same bitwise state-preserving select as T padding.
        cap = jax.tree_util.tree_leaves(state)[0].shape[0]
        xs_b = jnp.broadcast_to(xs, (cap,) + xs.shape)
        ys_b = jnp.broadcast_to(ys, (cap,) + ys.shape)
        act = active.reshape((cap,) + (1,) * ms.ndim)
        ms_b = jnp.where(
            act, jnp.broadcast_to(ms, (cap,) + ms.shape), 0.0
        )
        return gang_fit(state, xs_b, ys_b, ms_b)

    if mesh is not None:
        # device-sharded gang: one launch, the tenants axis laid across
        # the mesh, per-shard member iteration. in/out specs are pytree
        # PREFIXES — P("tenants") shards every leaf's leading (pipeline)
        # axis; P() replicates the shared-input batches so they ship once
        # and broadcast in-program on each shard. The wraps bind NEW
        # names: gang_fit_shared calls gang_fit late-bound, and wrapping
        # it in place would nest shard_maps.
        P = jax.sharding.PartitionSpec
        sh, rep = P("tenants"), P()
        sharded_fit = _shard_map(
            gang_fit, mesh=mesh, in_specs=(sh, sh, sh, sh), out_specs=sh,
            check_vma=False,
        )
        sharded_shared = _shard_map(
            gang_fit_shared, mesh=mesh, in_specs=(sh, sh, rep, rep, rep),
            out_specs=sh, check_vma=False,
        )
        sharded_predict = _shard_map(
            gang_predict, mesh=mesh, in_specs=(sh, sh), out_specs=sh,
            check_vma=False,
        )
        sharded_flat = _shard_map(
            gang_flat, mesh=mesh, in_specs=sh, out_specs=sh,
            check_vma=False,
        )
        return (
            jax.jit(sharded_fit, donate_argnums=0),
            jax.jit(sharded_shared, donate_argnums=0),
            jax.jit(sharded_predict),
            jax.jit(sharded_flat),
        )

    return (
        jax.jit(gang_fit, donate_argnums=0),
        jax.jit(gang_fit_shared, donate_argnums=0),
        jax.jit(gang_predict),
        jax.jit(gang_flat),
    )


class _LaunchResult:
    """Shared holder for one gang launch's ``[C, T]`` loss matrix. Created
    when staging opens a launch group, fulfilled (lazily) at launch, and
    materialized to numpy at most once — forcing the launch first if a
    learning-curve poll somehow reads it early."""

    __slots__ = ("_cohort", "_lazy", "_np")

    def __init__(self, cohort: "Cohort"):
        self._cohort: Optional[Cohort] = cohort
        self._lazy = None
        self._np: Optional[np.ndarray] = None

    def fulfill(self, losses) -> None:
        self._lazy = losses
        self._cohort = None

    def values(self) -> np.ndarray:
        if self._np is None:
            if self._lazy is None:
                cohort, self._cohort = self._cohort, None
                if cohort is not None:
                    cohort.launch()
            self._np = np.asarray(self._lazy)
            self._lazy = None
        return self._np


class _StagedLoss:
    """Lazy loss of a staged fit: floats (or arrays, for fit_many chains)
    exactly like the lazy device scalars the un-cohorted path returns."""

    __slots__ = ("_res", "_slot", "_t0", "_t1")

    def __init__(self, res: _LaunchResult, slot: int, t0: int,
                 t1: Optional[int] = None):
        self._res = res
        self._slot = slot
        self._t0 = t0
        self._t1 = t1

    def _resolve(self):
        vals = self._res.values()
        if self._t1 is None:
            return vals[self._slot, self._t0]
        return vals[self._slot, self._t0:self._t1]

    def __float__(self) -> float:
        return float(self._resolve())

    def __array__(self, dtype=None):
        return np.asarray(self._resolve(), dtype)


class Cohort:
    """Same-spec pipelines sharing one stacked state tree + gang programs.

    Slots: ``members[slot]`` is the attached pipeline or None; capacity is
    a power of two; churn reuses freed slots (compaction) and only a full
    cohort doubles capacity (a shape change XLA re-specializes once)."""

    def __init__(self, pipeline, use_vmap: bool, timer=None, n_shards: int = 1,
                 serve_timer=None):
        self.key = pipeline.cache_key
        self.use_vmap = use_vmap
        self.timer = timer
        # serving-launch timing (gang predict flushes) is accounted apart
        # from the fit flush path so launch_timing() can report both
        self.serve_timer = serve_timer
        # tenant-axis device sharding: with n_shards > 1 the stacked state
        # and every gang launch lay the leading pipeline axis across the
        # first n_shards local devices (mesh axis "tenants"); 1 = the
        # exact single-device pre-sharding path
        self.n_shards = max(int(n_shards), 1)
        self._mesh = _mesh_for(self.n_shards) if self.n_shards > 1 else None
        self._sharding = (
            jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec("tenants")
            )
            if self._mesh is not None
            else None
        )
        # guarded pipelines gang with guarded programs (the guard flag is
        # part of cache_key, so a cohort is uniformly guarded or not)
        self.guarded = pipeline.guard is not None
        programs = _GANG_CACHE.get((self.key, use_vmap, self.n_shards))
        if programs is None:
            programs = _build_gang_programs(
                pipeline.learner, pipeline.preps, pipeline.per_record,
                use_vmap, guarded=self.guarded, mesh=self._mesh,
            )
            _GANG_CACHE.put((self.key, use_vmap, self.n_shards), programs)
        self._gfit, self._gfit_shared, self._gpred, self._gflat = programs
        flat0, self._unravel = jax.flatten_util.ravel_pytree(
            pipeline._state["params"]
        )
        self._flat_size = int(flat0.size)
        self._junflat = jax.jit(
            lambda mat: jax.lax.map(self._unravel, mat)
        )
        self.capacity = 0
        self.members: List[Optional[Any]] = []
        self.n_active = 0
        self._free: List[int] = []
        self.stacked = None
        # host-side authoritative overrides, scattered before every launch
        self._host_state: Dict[int, dict] = {}
        self._pending_flat: Dict[int, np.ndarray] = {}
        # staging: persistent [capacity, T, B, ...] numpy buffers written
        # in place at stage time (no per-launch allocation or entry
        # lists); `_counts` tracks the staged depth per slot, and only
        # the staged mask region is re-zeroed after a launch — stale
        # x/y garbage under a zero mask is discarded bitwise in-program
        self._counts: Dict[int, int] = {}
        self._buf_x: Optional[np.ndarray] = None
        self._buf_y: Optional[np.ndarray] = None
        self._buf_m: Optional[np.ndarray] = None
        # shared-input detection: when every member's staged batch at each
        # depth is the SAME array object (the spoke's shared-ingest path
        # flushes one batcher to all members of an identical-stream
        # cohort), the launch runs the shared program over ONE [T, B, ...]
        # input instead of a [capacity, T, B, ...] stack — collapsing the
        # dominant host->device conversion by the member count
        self._share_first: Optional[int] = None
        self._share_rows: List[Tuple[Any, Any, Any]] = []
        self._all_shared = False
        self._next_result: Optional[_LaunchResult] = None
        # deferred protocol actions (sync points) run right after a launch
        self._post: List[Tuple[int, Callable[[], None]]] = []
        self._post_slots: set = set()
        self._flat_cache: Optional[np.ndarray] = None
        self._in_launch = False
        # persistent gang-predict staging pads, keyed by per-slot batch
        # shape (the serving plane's pow2 row buckets keep this small);
        # _pred_dirty tracks which slots each pad last wrote
        self._pred_scratch: Dict[tuple, np.ndarray] = {}
        self._pred_dirty: Dict[tuple, List[int]] = {}
        self.attach(pipeline)

    # --- tenant-axis sharding helpers ------------------------------------

    def _pin(self, tree):
        """Constrain a stacked pytree to the tenants sharding. Host writes
        and growth run as plain jnp ops whose output placement GSPMD
        chooses; this re-lays every leaf's leading axis across the mesh
        (a no-op copy when already correctly sharded). Identity when
        unsharded."""
        if self._sharding is None:
            return tree
        return jax.device_put(tree, self._sharding)

    def _stage_dev(self, host_view: np.ndarray):
        """Ship one staged gang input to the device(s). Unsharded: hand
        the numpy view to the dispatch (which copies to the one device).
        Sharded: transfer per shard — slots are laid out in contiguous
        shard blocks, so each device receives its own slice of the host
        buffer and the transfer fans out across the mesh instead of
        funneling the whole ``[C, T, B, ...]`` tensor through one
        device."""
        if self._sharding is None:
            return host_view
        return jax.device_put(host_view, self._sharding)

    def _member_pull(self, slot: int) -> dict:
        """One member's state slice out of the stacked tree. Sharded
        cohorts materialize it to HOST leaves: a slice stays committed to
        its owning mesh device, and downstream per-member ops (solo
        re-dispatch after detach, merge_from, checkpoint restore) would
        trip multi-device colocation checks mixing it with default-device
        arrays. Values are bitwise the device slice either way."""
        st = _tree_map(lambda l: l[slot], self.stacked)
        if self._sharding is not None:
            st = _tree_map(lambda l: np.asarray(l), st)
        return st

    def _host_state_leaves(self, state):
        """Scatter-side twin of :meth:`_member_pull`: writes into a
        sharded stack go in as host numpy leaves (uncommitted), never as
        arrays pinned to some other device."""
        if self._sharding is None:
            return state
        return _tree_map(lambda v: np.asarray(v), state)

    def _shard_of(self, slot: int) -> int:
        per = max(self.capacity // self.n_shards, 1)
        return min(slot // per, self.n_shards - 1)

    def shard_placement(self) -> List[int]:
        """Active member count per shard (length ``n_shards``) — the
        tenant placement the multi-tenant sweep records per mesh width."""
        counts = [0] * self.n_shards
        for slot, member in enumerate(self.members):
            if member is not None:
                counts[self._shard_of(slot)] += 1
        return counts

    def _pick_slot(self) -> int:
        """Claim a free slot. Single-shard: the lowest free slot (churn
        compaction). Sharded: the lowest free slot on the least-loaded
        shard — churn still compacts (within a shard, so no shape change
        and no recompile) while members stay balanced across the mesh."""
        if self.n_shards == 1:
            return self._free.pop()
        counts = self.shard_placement()
        slot = min(
            self._free, key=lambda s: (counts[self._shard_of(s)], s)
        )
        self._free.remove(slot)
        return slot

    # --- membership ------------------------------------------------------

    def attach(self, pipeline) -> int:
        """Adopt a pipeline: its local state seeds a (reused or new) slot
        and the pipeline's hot-path methods route through the cohort."""
        self.launch()
        if self.stacked is None:
            # first member: the smallest stack seeded from its state —
            # capacity 1 unsharded, one slot per shard when sharded (the
            # leading axis must cover the mesh; the duplicate rows are
            # inert until attach seeds them)
            cap = self.n_shards
            self.capacity = cap
            self.members = [pipeline] + [None] * (cap - 1)
            self.n_active = 1
            self._free = list(range(cap - 1, 0, -1))
            if cap == 1:
                self.stacked = _tree_map(
                    lambda l: jnp.asarray(l)[None], pipeline._state
                )
            else:
                self.stacked = self._pin(_tree_map(
                    lambda l: jnp.broadcast_to(
                        jnp.asarray(l)[None],
                        (cap,) + jnp.asarray(l).shape,
                    ),
                    pipeline._state,
                ))
            pipeline._cohort = self
            pipeline._slot = 0
            pipeline._state = None
            self._flat_cache = None
            return 0
        if not self._free:
            self._grow()
        slot = self._pick_slot()
        state = self._host_state_leaves(pipeline._state)
        self.stacked = self._pin(_tree_map(
            lambda leaf, v: leaf.at[slot].set(
                v if isinstance(v, np.ndarray) else jnp.asarray(v)
            ),
            self.stacked, state,
        ))
        self.members[slot] = pipeline
        self.n_active += 1
        pipeline._cohort = self
        pipeline._slot = slot
        pipeline._state = None
        self._flat_cache = None
        return slot

    def detach(self, pipeline) -> None:
        """Release a member: its slot's state materializes back into the
        pipeline and the slot returns to the free list for churn reuse."""
        self.launch()
        slot = pipeline._slot
        pipeline._state = self._member_pull(slot)
        pipeline._cohort = None
        pipeline._slot = -1
        self.members[slot] = None
        self.n_active -= 1
        self._host_state.pop(slot, None)
        self._pending_flat.pop(slot, None)
        self._free.append(slot)
        self._free.sort(reverse=True)  # reuse the lowest slot first

    def _grow(self) -> None:
        """Double capacity (power-of-two buckets): the new region is filled
        with duplicated rows — inert until a slot is seeded by attach.

        Sharded cohorts double EACH SHARD'S contiguous block in place
        (slot ``i*per + j`` remaps to ``i*2*per + j``): every member stays
        on its shard across growth, so placement balance survives and the
        one-time data movement is shard-local. Growth only happens from
        :meth:`attach`, right after a launch barrier — staging counts,
        launch groups and deferred actions are all empty, so only the
        membership maps and pending host writes carry slot keys."""
        old = self.capacity
        if self.n_shards == 1:
            self.stacked = _tree_map(
                lambda l: jnp.concatenate([l, l], axis=0), self.stacked
            )
            self.members.extend([None] * old)
            self._free.extend(range(old * 2 - 1, old - 1, -1))
            self._free.sort(reverse=True)
            self.capacity = old * 2
            return
        per = old // self.n_shards

        def dbl(l):
            blocks = l.reshape((self.n_shards, per) + l.shape[1:])
            blocks = jnp.concatenate([blocks, blocks], axis=1)
            return blocks.reshape((old * 2,) + l.shape[1:])

        self.stacked = self._pin(_tree_map(dbl, self.stacked))
        remap = {
            s: (s // per) * 2 * per + (s % per) for s in range(old)
        }
        new_members: List[Optional[Any]] = [None] * (old * 2)
        for s, member in enumerate(self.members):
            if member is not None:
                new_members[remap[s]] = member
                member._slot = remap[s]
        self.members = new_members
        self._host_state = {
            remap[s]: v for s, v in self._host_state.items()
        }
        self._pending_flat = {
            remap[s]: v for s, v in self._pending_flat.items()
        }
        self.capacity = old * 2
        self._free = sorted(
            (s for s in range(old * 2) if new_members[s] is None),
            reverse=True,
        )
        self._flat_cache = None

    # --- staging ----------------------------------------------------------

    def has_staged(self, slot: int) -> bool:
        return slot in self._counts

    def has_deferred(self, slot: int) -> bool:
        return slot in self._post_slots

    def after_launch(self, slot: int, cb: Callable[[], None]) -> None:
        self._post.append((slot, cb))
        self._post_slots.add(slot)

    def _open_group(self) -> _LaunchResult:
        if self._next_result is None:
            self._next_result = _LaunchResult(self)
        return self._next_result

    def _stage_room(self, slot: int, x: np.ndarray, y: np.ndarray,
                    m: np.ndarray, need: int) -> int:
        """Make room for ``need`` more staged steps on ``slot``; returns
        the slot's current depth (post any forced launch/realloc)."""
        if slot in self._post_slots:
            # a deferred sync point is pending for this member: it must run
            # (on the post-launch model) before the member's next fit
            self.launch()
        n = self._counts.get(slot, 0)
        if n + need > MAX_STAGE_DEPTH:
            self.launch()
            n = 0
        buf = self._buf_x
        if (
            buf is None
            or buf.shape[0] != self.capacity
            or buf.shape[2:] != x.shape
            or buf.shape[1] < n + need
        ):
            self._realloc_buffers(x, y, m, n + need)
            n = self._counts.get(slot, 0)  # a shape-mismatch realloc launches
        return n

    def _realloc_buffers(self, x, y, m, depth: int) -> None:
        t_alloc = _pow2(max(depth, 4))
        new_x = np.zeros((self.capacity, t_alloc) + x.shape, np.float32)
        new_y = np.zeros((self.capacity, t_alloc) + y.shape, np.float32)
        new_m = np.zeros((self.capacity, t_alloc) + m.shape, np.float32)
        if self._counts and self._buf_x is not None:
            if self._buf_x.shape[2:] != x.shape:
                # same-cohort batches always share a shape; a mismatch can
                # only arrive across a settle point
                self.launch()
                self._counts = {}
            else:
                c = min(self._buf_x.shape[0], self.capacity)
                t = min(self._buf_x.shape[1], t_alloc)
                new_x[:c, :t] = self._buf_x[:c, :t]
                new_y[:c, :t] = self._buf_y[:c, :t]
                new_m[:c, :t] = self._buf_m[:c, :t]
        self._buf_x, self._buf_y, self._buf_m = new_x, new_y, new_m

    def _materialize_shared(self) -> None:
        """Backfill the per-slot buffers of members that skipped their
        copies under shared detection; per-slot launching is valid after."""
        if not self._all_shared:
            return
        self._all_shared = False
        lead = self._share_first
        for slot, n in self._counts.items():
            if slot == lead:
                continue
            self._buf_x[slot, :n] = self._buf_x[lead, :n]
            self._buf_y[slot, :n] = self._buf_y[lead, :n]
            self._buf_m[slot, :n] = self._buf_m[lead, :n]
        self._share_rows = []

    def stage_fit(self, slot: int, x, y, mask) -> _StagedLoss:
        x = np.asarray(x)
        y = np.asarray(y)
        m = np.asarray(mask)
        n = self._stage_room(slot, x, y, m, 1)
        res = self._open_group()
        if not self._counts:
            # first stage of a launch group: it leads shared detection
            self._share_first = slot
            self._share_rows = [(x, y, m)]
            self._all_shared = True
        elif self._all_shared:
            if slot == self._share_first and n == len(self._share_rows):
                self._share_rows.append((x, y, m))
            elif (
                slot != self._share_first
                and n < len(self._share_rows)
                and x is self._share_rows[n][0]
                and y is self._share_rows[n][1]
                and m is self._share_rows[n][2]
            ):
                # identical objects: the leader's buffer row IS this
                # member's batch — no copy
                self._counts[slot] = n + 1
                return _StagedLoss(res, slot, n)
            else:
                self._materialize_shared()
        self._buf_x[slot, n] = x
        self._buf_y[slot, n] = y
        self._buf_m[slot, n] = m
        self._counts[slot] = n + 1
        return _StagedLoss(res, slot, n)

    def stage_fit_many(self, slot: int, xs, ys, masks) -> _StagedLoss:
        xs = np.asarray(xs)
        ys = np.asarray(ys)
        ms = np.asarray(masks)
        depth = int(xs.shape[0])
        n = self._stage_room(slot, xs[0], ys[0], ms[0], depth)
        self._materialize_shared()  # chained drains never share objects
        res = self._open_group()
        self._buf_x[slot, n : n + depth] = xs
        self._buf_y[slot, n : n + depth] = ys
        self._buf_m[slot, n : n + depth] = ms
        self._counts[slot] = n + depth
        return _StagedLoss(res, slot, n, n + depth)

    # --- launching --------------------------------------------------------

    def launch(self) -> None:
        """Gang barrier: execute every staged fit, then run the deferred
        protocol actions (which may stage/launch more — e.g. a sync push
        whose round release drains blocked batches)."""
        if self._in_launch:
            self._run_staged()
            return
        self._in_launch = True
        try:
            while True:
                self._run_staged()
                if not self._post:
                    break
                post, self._post = self._post, []
                self._post_slots = set()
                for _slot, cb in post:
                    cb()
        finally:
            self._in_launch = False

    def _note_launch(self, slot: int) -> None:
        member = self.members[slot] if 0 <= slot < self.capacity else None
        if member is not None and member.on_launch is not None:
            member.on_launch()

    def _timed(self):
        return self.timer if self.timer is not None else contextlib.nullcontext()

    def _timed_serve(self):
        """Gang predict launches (forecast serving flushes) time into the
        serve timer, not the fit flush timer, so launch_timing() reports
        the serving plane's launch percentiles separately."""
        if self.serve_timer is not None:
            return self.serve_timer
        return self._timed()

    def _run_staged(self) -> None:
        self._apply_host_writes()
        if not self._counts:
            return
        shared = (
            self._all_shared
            and len(self._counts) > 1
            and len(set(self._counts.values())) == 1
        )
        if not shared:
            self._materialize_shared()
        lead = self._share_first
        self._share_first = None
        self._share_rows = []
        self._all_shared = False
        counts, self._counts = self._counts, {}
        result, self._next_result = self._next_result, None
        t_pad = _pow2(max(counts.values()))
        self._note_launch(min(counts))
        if shared:
            # one [T, B, ...] input for the whole cohort: the conversion
            # cost stops scaling with the member count
            xs = self._buf_x[lead, :t_pad]
            ys = self._buf_y[lead, :t_pad]
            ms = self._buf_m[lead, :t_pad]
            active = np.zeros((self.capacity,), np.bool_)
            active[list(counts)] = True
            with self._timed():
                self.stacked, losses = self._gfit_shared(
                    self.stacked, active, xs, ys, ms
                )
            self._buf_m[lead, :t_pad] = 0.0
            if self.guarded:
                losses = self._note_health(losses, counts)
        else:
            # sharded cohorts ship each device its own contiguous block of
            # the slot-major staging buffers (_stage_dev); unsharded, the
            # numpy views go straight to the dispatch. Either way the
            # transfer copies before the call returns, so reusing the
            # staging buffers after is safe
            xs = self._stage_dev(self._buf_x[:, :t_pad])
            ys = self._stage_dev(self._buf_y[:, :t_pad])
            ms = self._stage_dev(self._buf_m[:, :t_pad])
            with self._timed():
                self.stacked, losses = self._gfit(self.stacked, xs, ys, ms)
            # re-zero ONLY the staged mask region: everything else is
            # already zero, and stale x/y rows under a zero mask are inert
            for slot, n in counts.items():
                self._buf_m[slot, :n] = 0.0
            if self.guarded:
                losses = self._note_health(losses, counts)
        if result is not None:
            result.fulfill(losses)
        self._flat_cache = None

    def _note_health(self, gang_out, counts):
        """Split a guarded gang launch's ``(losses, sq_norm[C])`` output:
        hand each launched member its health scalar and return the plain
        loss matrix for the launch result. The [C] health vector is
        materialized ONCE here (the launch just ran, so this is one small
        transfer) — per-slot lazy device slices would cost every member
        its own blocking transfer at the next guard tick, C tiny syncs in
        exactly the dispatch-overhead regime cohorts exist to collapse.
        Sharded cohorts gather the vector per shard in one parallel
        device_get (guard.gang_health_values)."""
        losses, sq_norm = gang_out
        vals = gang_health_values(sq_norm)
        for slot, n in counts.items():
            member = self.members[slot]
            if member is not None and member.guard is not None:
                member.guard.note(float(vals[slot]), fits=n)
        return losses

    def _apply_host_writes(self) -> None:
        """Scatter host-side authoritative state (checkouts, written flat
        rows) back into the stacked tree before the next program runs."""
        if self._host_state:
            for slot, st in self._host_state.items():
                st = self._host_state_leaves(st)
                self.stacked = _tree_map(
                    lambda leaf, v: leaf.at[slot].set(
                        v if isinstance(v, np.ndarray) else jnp.asarray(v)
                    ),
                    self.stacked, st,
                )
            self.stacked = self._pin(self.stacked)
            self._host_state.clear()
            self._flat_cache = None
        if self._pending_flat:
            slots = sorted(self._pending_flat)
            k = _pow2(len(slots))
            mat = np.zeros((k, self._flat_size), np.float32)
            for i, s in enumerate(slots):
                mat[i] = self._pending_flat[s]
            # pad with duplicates of the first row/index: a duplicate
            # scatter index writes the same value, so the pow2 bucket is
            # free of shape churn without perturbing any other slot
            mat[len(slots):] = mat[0]
            idx = np.asarray(
                slots + [slots[0]] * (k - len(slots)), np.int32
            )
            new_params = self._junflat(jnp.asarray(mat))
            if self._sharding is not None:
                # host-leaf updates + numpy indices: the scatter operands
                # must not be committed to one device while the target is
                # mesh-sharded
                new_params = _tree_map(lambda l: np.asarray(l), new_params)
                self.stacked["params"] = self._pin(_tree_map(
                    lambda leaf, u: leaf.at[idx].set(u),
                    self.stacked["params"], new_params,
                ))
            else:
                jidx = jnp.asarray(idx)
                self.stacked["params"] = _tree_map(
                    lambda leaf, u: leaf.at[jidx].set(u),
                    self.stacked["params"], new_params,
                )
            self._pending_flat.clear()

    # --- member state access ---------------------------------------------

    def checkout(self, slot: int) -> dict:
        """Authoritative (host-cached) state dict for one member. The SAME
        dict is returned until the next launch scatters it back, so callers
        that mutate entries in place (checkpoint restore, merge_from) see
        their writes land in the stacked tree."""
        st = self._host_state.get(slot)
        if st is None:
            self.launch()
            st = self._member_pull(slot)
            pend = self._pending_flat.pop(slot, None)
            if pend is not None:
                st["params"] = self._unravel(jnp.asarray(pend))
            self._host_state[slot] = st
            self._flat_cache = None  # caller may mutate params
        return st

    def set_member_state(self, slot: int, value: dict) -> None:
        self.launch()
        self._pending_flat.pop(slot, None)
        self._host_state[slot] = value
        self._flat_cache = None

    def peek_state(self, slot: int) -> dict:
        """Read-only member state snapshot (predict/evaluate)."""
        st = self._host_state.get(slot)
        if st is not None:
            return st
        self.launch()
        return self._member_pull(slot)

    def member_flat(self, slot: int):
        """(flat params row copy, unravel) — the gang get_flat: the [C, P]
        flat matrix is computed in ONE launch and cached; row writes keep
        the cache warm instead of invalidating it."""
        st = self._host_state.get(slot)
        if st is not None:
            flat, _ = jax.flatten_util.ravel_pytree(st["params"])
            return np.array(flat), self._unravel
        self.launch()
        if self._flat_cache is None:
            self._note_launch(slot)
            with self._timed():
                # writable copy: row writes keep the cache warm
                self._flat_cache = np.array(
                    self._gflat(self.stacked["params"])
                )
        return self._flat_cache[slot].copy(), self._unravel

    def set_member_flat(self, slot: int, flat: np.ndarray) -> None:
        if slot in self._host_state:
            self._host_state[slot]["params"] = self._unravel(
                jnp.asarray(flat)
            )
            return
        row = np.array(flat, np.float32, copy=True)
        self._pending_flat[slot] = row
        if self._flat_cache is not None:
            self._flat_cache[slot] = row

    def member_cum_loss(self, slot: int) -> float:
        st = self._host_state.get(slot)
        if st is not None:
            return float(st["cum_loss"])
        self.launch()
        return float(self.stacked["cum_loss"][slot])

    def predict_rows(self, entries: List[Tuple[int, np.ndarray]]) -> np.ndarray:
        """Gang forecast serving: one padded predict launch over the whole
        cohort. ``entries`` are ``(slot, padded [B, ...] batch)`` pairs —
        every batch the same shape, any number of rows (the per-record
        path passes one PREDICT_BATCH pad per slot; the serving plane
        passes multi-row queues, batching across stream positions AND
        tenants). The result indexes ``[slot, row]`` per participant.

        The ``[capacity, B, ...]`` staging pad is a persistent per-shape
        scratch (the dispatch copies host buffers to device before
        returning, so reuse is safe — same contract as the fit staging
        buffers); only previously-written slots re-zero."""
        self.launch()
        x0 = entries[0][1]
        shape = (self.capacity,) + x0.shape
        xs = self._pred_scratch.get(shape[1:])
        if xs is None or xs.shape != shape:
            xs = np.zeros(shape, np.float32)
            self._pred_scratch[shape[1:]] = xs
            self._pred_dirty.pop(shape[1:], None)
        else:
            for slot in self._pred_dirty.get(shape[1:], ()):
                xs[slot] = 0.0
        for slot, xb in entries:
            xs[slot] = xb
        self._pred_dirty[shape[1:]] = [slot for slot, _ in entries]
        self._note_launch(entries[0][0])
        with self._timed_serve():
            out = self._gpred(self.stacked, self._stage_dev(xs))
        return np.asarray(out)


class CohortEngine:
    """Per-spoke cohort manager: groups eligible pipelines by jit-cache key
    and forms cohorts per the configured mode/threshold."""

    def __init__(self, config, timer=None, serve_timer=None):
        mode = str(getattr(config, "cohort", "off")).lower()
        self.mode = mode if mode in ("auto", "on") else "off"
        self.min_members = (
            1 if self.mode == "on"
            else max(int(getattr(config, "cohort_min", 8)), 1)
        )
        impl = str(getattr(config, "cohort_impl", "auto")).lower()
        if impl == "auto":
            self.use_vmap = jax.default_backend() != "cpu"
        else:
            self.use_vmap = impl == "vmap"
        # tenant-axis device sharding (JobConfig.cohort_shards): resolved
        # once per engine; every cohort this engine forms shares the width
        self.n_shards = resolve_cohort_shards(config)
        self.timer = timer
        self.serve_timer = serve_timer
        self.cohorts: Dict[Any, Cohort] = {}
        self._pool: Dict[Any, List[Any]] = {}

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    @staticmethod
    def eligible(pipeline) -> bool:
        """Dense, device-side pipelines with float32 flat params gang;
        host-side (HT), SingleLearner-forced (the model lives on the hub,
        spoke replicas only serve) and sparse-COO learners keep the
        per-pipeline path."""
        from omldm_tpu.learners.registry import SINGLE_LEARNER_ONLY

        if pipeline.cache_key is None or pipeline.learner.host_side:
            return False
        if pipeline.learner.name in SINGLE_LEARNER_ONLY:
            return False
        if getattr(pipeline.learner, "sparse", False):
            return False
        if pipeline._cohort is not None:
            return False
        flat, _ = jax.flatten_util.ravel_pytree(pipeline._state["params"])
        return flat.dtype == jnp.float32

    def consider(self, pipeline) -> None:
        """Offer a (new) pipeline: joins its key's cohort, or pools until
        the auto threshold forms one."""
        if self.mode == "off" or not self.eligible(pipeline):
            return
        key = pipeline.cache_key
        cohort = self.cohorts.get(key)
        if cohort is not None:
            cohort.attach(pipeline)
            return
        pool = self._pool.setdefault(key, [])
        pool.append(pipeline)
        if len(pool) >= self.min_members:
            cohort = Cohort(
                pool[0], self.use_vmap, timer=self.timer,
                n_shards=self.n_shards, serve_timer=self.serve_timer,
            )
            for p in pool[1:]:
                cohort.attach(p)
            self.cohorts[key] = cohort
            del self._pool[key]

    def retire(self, pipeline) -> None:
        cohort = pipeline._cohort
        if cohort is not None:
            cohort.detach(pipeline)
            if cohort.n_active == 0:
                self.cohorts.pop(cohort.key, None)
            return
        pool = self._pool.get(getattr(pipeline, "cache_key", None))
        if pool and pipeline in pool:
            pool.remove(pipeline)

    def flush(self) -> None:
        """Gang barrier: launch every cohort's staged work."""
        for cohort in self.cohorts.values():
            cohort.launch()

    def detach_all(self) -> None:
        """Dissolve every cohort (rescale absorb, shutdown): members get
        their state back and run per-pipeline until re-considered."""
        for cohort in list(self.cohorts.values()):
            for member in list(cohort.members):
                if member is not None:
                    cohort.detach(member)
        self.cohorts.clear()
        self._pool.clear()


class GangAverager:
    """Deferred, vectorized model averaging for same-protocol cohort
    members' parameter-server shards.

    A hub whose round completes inside an active window stages its stacked
    ``[W, P]`` contribution matrix; at the window's exit every same-shape
    group averages in ONE ``[M, W, P]`` numpy reduction (bit-identical to
    the per-hub ``mean(axis=0)``) and the hubs broadcast their releases.
    Outside a window ``active`` is False and hubs average immediately — the
    exact pre-cohort behavior."""

    def __init__(self):
        self._depth = 0
        self._staged: List[Tuple[Any, np.ndarray]] = []

    @property
    def active(self) -> bool:
        return self._depth > 0

    @contextlib.contextmanager
    def window(self):
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            if self._depth == 0:
                self.flush()

    def stage(self, hub_node, stacked: np.ndarray) -> None:
        self._staged.append((hub_node, stacked))

    def flush(self) -> None:
        # releases can complete further rounds synchronously (a released
        # worker drains, pushes, and closes the next round): loop until dry
        while self._staged:
            staged, self._staged = self._staged, []
            groups: Dict[Tuple[int, ...], List[Tuple[Any, np.ndarray]]] = {}
            for node, mat in staged:
                groups.setdefault(mat.shape, []).append((node, mat))
            for items in groups.values():
                if len(items) == 1:
                    node, mat = items[0]
                    node._finish_round(mat.mean(axis=0))
                    continue
                means = np.stack([m for _, m in items]).mean(axis=1)
                for (node, _), avg in zip(items, means):
                    node._finish_round(avg)
