"""Hub: the parameter-server-side runtime.

Reference counterpart: ``FlinkHub`` + ``HubLogic`` (FlinkHub.scala:25-197,
HubLogic.scala:15-35): one keyed instance per (networkId, hubId); worker
messages arriving before hub creation are cached (20_000-message DataSet,
FlinkHub.scala:70-87) and drained after creation; in test mode the hub
extracts per-hub ``Statistics`` including incremental learning-curve slices
from the PS (FlinkHub.scala:88-157).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from omldm_tpu.api.requests import Request
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.protocols.centralized import CentralizedMLServer
from omldm_tpu.protocols.registry import make_hub_node, resolve_protocol
from omldm_tpu.runtime.databuffers import DataSet
from omldm_tpu.runtime.messages import (
    OP_NACK,
    ReceiveWindow,
    StreamSequencer,
    channel_chaos_spec,
    channel_window_size,
    payload_size,
    reliability_armed,
)


class Hub:
    """One (networkId, hubId) parameter-server shard."""

    def __init__(
        self,
        network_id: int,
        hub_id: int,
        request: Request,
        dim: int,
        config: JobConfig,
        reply: Callable,       # (worker_id, op, payload)
        broadcast: Callable,   # (op, payload)
    ):
        self.network_id = network_id
        self.hub_id = hub_id
        tc = request.training_configuration
        protocol = resolve_protocol(
            tc.protocol, request.learner.name, config.parallelism
        )
        self.protocol = protocol
        self.node = make_hub_node(
            protocol,
            network_id,
            hub_id,
            config.parallelism,
            tc.hub_parallelism,
            tc,
            reply,
            broadcast,
        )
        # stats carry the resolved protocol, not the requested one (the
        # forcing rules of FlinkSpoke.scala:203-215 may have overridden it)
        self.node.stats.protocol = protocol
        # reliable channel: one receive window per worker stream, armed
        # per pipeline (None => the exact pre-reliable receive path)
        self._windows: Optional[Dict[int, ReceiveWindow]] = (
            {}
            if reliability_armed(tc, channel_chaos_spec(config))
            else None
        )
        self._window_size = channel_window_size(tc)
        self._quiesced = False
        # SingleLearner: the central model lives here (FlinkHub.scala:128-153)
        if isinstance(self.node, CentralizedMLServer):
            self.node.attach_pipeline(
                MLPipeline(
                    request.learner,
                    request.preprocessors,
                    dim=dim,
                    rng=jax.random.PRNGKey(request.id),
                    per_record=tc.per_record,
                )
            )
            # hub-side fits are host-plane program launches too
            stats = self.node.stats
            self.node.pipeline.on_launch = (
                lambda: stats.update_stats(program_launches=1)
            )

    def receive(
        self, worker_id: int, op: str, payload: Any, seq: Optional[int] = None
    ) -> None:
        """Worker->hub receive boundary.

        With the reliable channel armed, every message passes the
        per-worker :class:`ReceiveWindow` first: duplicates drop (counted),
        out-of-order messages hold until their gap fills, and a gap that
        outlives the window fast-forwards + NACKs the worker for an
        authoritative re-push (its codec delta stream re-anchors too).
        Liveness is clocked here as well — a message from anyone is the
        only timer a streaming hub gets."""
        if self.node.events is not None:
            # transport stamp of the message being dispatched: the
            # flight-recorder events this receive triggers (rejection,
            # retirement, resync, liveness re-admission) carry it, which
            # is what lets a fleet bundle order the cross-process chain.
            # Held/reordered deliveries keep the triggering message's
            # stamp — the decision still happened at this receive.
            self.node._rx_stamp = (
                (self.network_id, seq) if seq is not None else None
            )
        if self.node.liveness_armed:
            self.node.note_worker(worker_id)
            self.node.check_liveness()
        if seq is None or self._windows is None:
            self._dispatch(worker_id, op, payload)
            return
        window = self._windows.get(worker_id)
        if window is None:
            # a window born after quiesce (every earlier message from this
            # worker was lost) starts in pass-through, or its first
            # terminate-time push would be held forever
            window = self._windows[worker_id] = ReceiveWindow(
                self._window_size, passthrough=self._quiesced
            )
        res = window.offer(seq, op, payload)
        if res.duplicates:
            self.node.stats.update_stats(duplicates_dropped=res.duplicates)
        if res.gap:
            self.node.stats.update_stats(gaps_resynced=1)
            if self.node.events is not None:
                from omldm_tpu.runtime.events import GAP_RESYNC

                self.node.events.record(
                    GAP_RESYNC, "window_gap", pipeline=self.network_id,
                    worker=worker_id, stamp=(self.network_id, seq),
                    side="hub", hub=self.hub_id,
                    expected=res.gap_from, got=res.gap_to,
                )
            if self.node.codec is not None:
                # deltas were lost: the rx base no longer matches the
                # sender's; drop it and make the sender re-anchor
                self.node.codec.reset_rx_stream(f"w{worker_id}>h{self.hub_id}")
            self.node.nack_worker(worker_id)
        for d_op, d_payload in res.deliver:
            self._dispatch(worker_id, d_op, d_payload)

    def _dispatch(self, worker_id: int, op: str, payload: Any) -> None:
        # transport boundary: count the bytes that actually crossed the
        # wire (encoded size when the worker compressed, raw size
        # otherwise) and decode ONCE, so protocol logic and its logical
        # bytesShipped accounting never see encoded leaves
        self.node.stats.update_stats(bytes_on_wire=payload_size(payload))
        if op == OP_NACK:
            self.node.on_nack(worker_id, payload)
            return
        if self.node.codec is not None:
            payload = self.node.codec.decode(payload)
        # model-integrity delta admission (trainingConfiguration.guard):
        # a non-finite or norm-exploding worker update is rejected HERE,
        # after decode but before protocol logic or round accounting can
        # fold it into shared state; guard_admit resyncs (and eventually
        # retires) the offender. Unarmed (default): one attribute read.
        if self.node.guard_armed:
            if self.node.guard_admit(worker_id, op, payload) is not None:
                return
        self.node.receive(worker_id, op, payload)

    def flush_windows(self) -> None:
        """Stream quiesce: deliver everything the receive windows still
        hold (pending gaps will never fill once the stream ended)."""
        self._quiesced = True
        if not self._windows:
            return
        # snapshot: dispatching a held message can synchronously complete
        # a round whose release makes a worker push back into receive(),
        # creating a NEW window mid-iteration
        for worker_id, window in list(self._windows.items()):
            for op, payload in window.flush():
                self._dispatch(worker_id, op, payload)

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: retired workers' receive windows vanish with them
        (a reused slot restarts its stream at seq 0 against a FRESH
        window), then the protocol node prunes its own round state."""
        if self._windows:
            for w in [w for w in self._windows if w >= n_workers]:
                del self._windows[w]
        self.node.set_parallelism(n_workers)

    def statistics(self) -> Statistics:
        return self.node.stats

    def on_terminate(self) -> None:
        self.node.on_terminate()
        # hub-side transport-codec wall time folds into this shard's
        # statistics exactly once, at terminate (the spoke-side twin
        # delta-folds at query/terminate; see Statistics.codec_*_seconds)
        codec = getattr(self.node, "codec", None)
        if codec is not None:
            self.node.stats.update_stats(
                codec_encode_seconds=codec.encode_seconds,
                codec_decode_seconds=codec.decode_seconds,
            )


class HubManager:
    """Routes worker->hub traffic; caches messages that beat hub creation
    (FlinkHub.scala:70-87, StateAccumulators.scala:128-146)."""

    def __init__(self, config: JobConfig, reply_to_spoke: Callable):
        self.config = config
        self.hubs: Dict[Tuple[int, int], Hub] = {}
        # (network_id, hub_id, worker_id, op, payload, seq)
        self._reply_to_spoke = reply_to_spoke
        self._pre_creation: Dict[Tuple[int, int], DataSet] = {}
        # per-(network, hub) downstream sequencers (hub->worker streams),
        # built only for reliability-armed pipelines
        self._down_seq: Dict[Tuple[int, int], Optional[StreamSequencer]] = {}
        # cached any-shard-armed flag: the per-record liveness tick on the
        # data hot path must cost one attribute read when nothing is armed
        self._any_liveness = False
        # flight-recorder journal (runtime/events.EventJournal) handed to
        # every shard's protocol node at creation; None = unarmed
        self.events = None
        # armed-path striding: the full every-hub walk runs every
        # `liveness_stride` events or when the deadline (min armed
        # workerTimeout / 4) lapses — not once per record/chunk
        self._liveness_stride = max(
            int(getattr(config, "liveness_stride", 16)), 1
        )
        self._liveness_tick = 0
        self._liveness_deadline = 0.0
        self._liveness_period = 0.0
        # cohort gang averaging: same-cohort PS shards stage completed
        # rounds inside a job event window and average in one stacked
        # [M, W, P] numpy reduction (bit-identical mean). With the tenant
        # axis device-sharded, the member flat slices the hubs stage come
        # out of the cohort's ONE-launch sharded [C, P] flat matrix — the
        # reduction itself stays host-side and exact either way
        self.gang = None
        if str(getattr(config, "cohort", "off")).lower() in ("auto", "on"):
            from omldm_tpu.runtime.cohort import GangAverager

            self.gang = GangAverager()

    def create_hub(self, request: Request, hub_id: int, dim: int) -> Hub:
        key = (request.id, hub_id)
        if key in self.hubs:
            return self.hubs[key]
        net_id = request.id
        armed = reliability_armed(
            request.training_configuration, channel_chaos_spec(self.config)
        )
        seqr = StreamSequencer() if armed else None
        self._down_seq[key] = seqr

        def reply(worker_id: int, op: str, payload: Any) -> None:
            self._reply_to_spoke(
                net_id, hub_id, worker_id, op, payload,
                seqr.next(worker_id) if seqr is not None else None,
            )

        def broadcast(op: str, payload: Any) -> None:
            # a broadcast is one reliable stream PER destination: each
            # worker's copy carries that worker's next sequence number
            for w in range(self.config.parallelism):
                self._reply_to_spoke(
                    net_id, hub_id, w, op, payload,
                    seqr.next(w) if seqr is not None else None,
                )

        hub = Hub(net_id, hub_id, request, dim, self.config, reply, broadcast)
        hub.node.gang = self.gang
        # per-pipeline opt-out (trainingConfiguration.events = false): an
        # opted-out pipeline's shards never record, even with the job
        # plane armed — the spoke-side events_cfg rule
        if self.events is not None:
            from omldm_tpu.runtime.events import events_armed_for

            if events_armed_for(
                request.training_configuration,
                getattr(self.config, "events", ""),
            ):
                hub.node.events = self.events
        # the tenant-mesh width gauge (Statistics.cohort_shards) is NOT
        # stamped here from config: a pipeline that never cohorts (sparse,
        # host-side, pooled below cohort_min) must report 0, so only the
        # spoke-side fold of the ACTUALLY-engaged shard count
        # (Spoke.emit_query_response) feeds it
        self.hubs[key] = hub
        self._any_liveness = self._any_liveness or hub.node.liveness_armed
        self._refresh_liveness_period()
        # drain the pre-creation cache (FlinkHub.scala:70-87)
        cached = self._pre_creation.pop(key, None)
        if cached is not None:
            for worker_id, op, payload, seq in cached:
                hub.receive(worker_id, op, payload, seq)
        return hub

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: every PS shard updates its expected worker count
        and drops retired workers' round state (the reference's shared
        spokeParallelism IntWrapper reaches hub logic the same way).
        Downstream sequencers to retired workers reset too, so a reused
        slot's stream restarts at seq 0 against the fresh spoke window."""
        for seqr in self._down_seq.values():
            if seqr is not None:
                seqr.drop_streams(
                    [w for w in seqr._next if isinstance(w, int) and w >= n_workers]
                )
        for hub in self.hubs.values():
            hub.set_parallelism(n_workers)

    def delete_network(self, network_id: int) -> None:
        for key in [k for k in self.hubs if k[0] == network_id]:
            del self.hubs[key]
        for key in [k for k in self._pre_creation if k[0] == network_id]:
            del self._pre_creation[key]
        for key in [k for k in self._down_seq if k[0] == network_id]:
            del self._down_seq[key]
        self._any_liveness = any(
            h.node.liveness_armed for h in self.hubs.values()
        )
        self._refresh_liveness_period()

    def route(
        self,
        network_id: int,
        hub_id: int,
        worker_id: int,
        op: str,
        payload: Any,
        seq: Optional[int] = None,
    ) -> None:
        hub = self.hubs.get((network_id, hub_id))
        if hub is None:
            cache = self._pre_creation.setdefault(
                (network_id, hub_id), DataSet(self.config.hub_cache_cap)
            )
            cache.append((worker_id, op, payload, seq))
            return
        hub.receive(worker_id, op, payload, seq)

    def flush_windows(self) -> None:
        """Quiesce every shard's receive windows (stream end)."""
        for hub in self.hubs.values():
            hub.flush_windows()

    @property
    def any_liveness(self) -> bool:
        return self._any_liveness

    def _refresh_liveness_period(self) -> None:
        """Deadline half of the stride: re-walk at least every quarter of
        the tightest armed worker timeout, however sparse the events."""
        timeouts = [
            h.node.worker_timeout_s
            for h in self.hubs.values()
            if h.node.liveness_armed
        ]
        self._liveness_period = min(timeouts) / 4.0 if timeouts else 0.0
        self._liveness_deadline = 0.0  # re-walk on the next armed event

    def check_liveness(self, force: bool = False) -> None:
        """Clock every liveness-armed shard's worker-deadline check. The
        job calls this from the DATA path: when a silent worker has the
        whole fleet blocked on a barrier, no protocol message ever reaches
        ``Hub.receive`` to run the check — but records keep streaming, so
        they are the clock that frees the round. One flag read when no
        pipeline armed liveness (the default hot path); when armed, the
        every-hub walk is STRIDED — every `liveness_stride` events, or
        when a quarter of the tightest worker timeout passed since the
        last walk — so a heavy record stream pays one counter increment
        per event, not a hub walk."""
        if not self._any_liveness:
            return
        self._liveness_tick += 1
        if not force and self._liveness_tick < self._liveness_stride:
            now = time.monotonic()
            if now < self._liveness_deadline:
                return
        self._liveness_tick = 0
        self._liveness_deadline = time.monotonic() + self._liveness_period
        for hub in self.hubs.values():
            if hub.node.liveness_armed:
                hub.node.check_liveness()

    def network_statistics(self, network_id: int) -> Optional[Statistics]:
        """Merged cross-hub statistics for one pipeline
        (StateAccumulators.scala:54-126)."""
        stats = [
            h.statistics() for (nid, _), h in self.hubs.items() if nid == network_id
        ]
        if not stats:
            return None
        merged = stats[0]
        for s in stats[1:]:
            merged = merged.merge(s)
        return merged

    def on_terminate(self) -> None:
        for hub in self.hubs.values():
            hub.on_terminate()
