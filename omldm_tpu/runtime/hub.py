"""Hub: the parameter-server-side runtime.

Reference counterpart: ``FlinkHub`` + ``HubLogic`` (FlinkHub.scala:25-197,
HubLogic.scala:15-35): one keyed instance per (networkId, hubId); worker
messages arriving before hub creation are cached (20_000-message DataSet,
FlinkHub.scala:70-87) and drained after creation; in test mode the hub
extracts per-hub ``Statistics`` including incremental learning-curve slices
from the PS (FlinkHub.scala:88-157).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax

from omldm_tpu.api.requests import Request
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.protocols.centralized import CentralizedMLServer
from omldm_tpu.protocols.registry import make_hub_node, resolve_protocol
from omldm_tpu.runtime.databuffers import DataSet
from omldm_tpu.runtime.messages import payload_size


class Hub:
    """One (networkId, hubId) parameter-server shard."""

    def __init__(
        self,
        network_id: int,
        hub_id: int,
        request: Request,
        dim: int,
        config: JobConfig,
        reply: Callable,       # (worker_id, op, payload)
        broadcast: Callable,   # (op, payload)
    ):
        self.network_id = network_id
        self.hub_id = hub_id
        tc = request.training_configuration
        protocol = resolve_protocol(
            tc.protocol, request.learner.name, config.parallelism
        )
        self.protocol = protocol
        self.node = make_hub_node(
            protocol,
            network_id,
            hub_id,
            config.parallelism,
            tc.hub_parallelism,
            tc,
            reply,
            broadcast,
        )
        # stats carry the resolved protocol, not the requested one (the
        # forcing rules of FlinkSpoke.scala:203-215 may have overridden it)
        self.node.stats.protocol = protocol
        # SingleLearner: the central model lives here (FlinkHub.scala:128-153)
        if isinstance(self.node, CentralizedMLServer):
            self.node.attach_pipeline(
                MLPipeline(
                    request.learner,
                    request.preprocessors,
                    dim=dim,
                    rng=jax.random.PRNGKey(request.id),
                    per_record=tc.per_record,
                )
            )

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        # transport boundary: count the bytes that actually crossed the
        # wire (encoded size when the worker compressed, raw size
        # otherwise) and decode ONCE, so protocol logic and its logical
        # bytesShipped accounting never see encoded leaves
        self.node.stats.update_stats(bytes_on_wire=payload_size(payload))
        if self.node.codec is not None:
            payload = self.node.codec.decode(payload)
        self.node.receive(worker_id, op, payload)

    def statistics(self) -> Statistics:
        return self.node.stats

    def on_terminate(self) -> None:
        self.node.on_terminate()


class HubManager:
    """Routes worker->hub traffic; caches messages that beat hub creation
    (FlinkHub.scala:70-87, StateAccumulators.scala:128-146)."""

    def __init__(self, config: JobConfig, reply_to_spoke: Callable):
        self.config = config
        self.hubs: Dict[Tuple[int, int], Hub] = {}
        # (network_id, hub_id, worker_id, op, payload)
        self._reply_to_spoke = reply_to_spoke
        self._pre_creation: Dict[Tuple[int, int], DataSet] = {}

    def create_hub(self, request: Request, hub_id: int, dim: int) -> Hub:
        key = (request.id, hub_id)
        if key in self.hubs:
            return self.hubs[key]
        net_id = request.id

        def reply(worker_id: int, op: str, payload: Any) -> None:
            self._reply_to_spoke(net_id, hub_id, worker_id, op, payload)

        def broadcast(op: str, payload: Any) -> None:
            for w in range(self.config.parallelism):
                self._reply_to_spoke(net_id, hub_id, w, op, payload)

        hub = Hub(net_id, hub_id, request, dim, self.config, reply, broadcast)
        self.hubs[key] = hub
        # drain the pre-creation cache (FlinkHub.scala:70-87)
        cached = self._pre_creation.pop(key, None)
        if cached is not None:
            for worker_id, op, payload in cached:
                hub.receive(worker_id, op, payload)
        return hub

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: every PS shard updates its expected worker count
        and drops retired workers' round state (the reference's shared
        spokeParallelism IntWrapper reaches hub logic the same way)."""
        for hub in self.hubs.values():
            hub.node.set_parallelism(n_workers)

    def delete_network(self, network_id: int) -> None:
        for key in [k for k in self.hubs if k[0] == network_id]:
            del self.hubs[key]
        for key in [k for k in self._pre_creation if k[0] == network_id]:
            del self._pre_creation[key]

    def route(
        self, network_id: int, hub_id: int, worker_id: int, op: str, payload: Any
    ) -> None:
        hub = self.hubs.get((network_id, hub_id))
        if hub is None:
            cache = self._pre_creation.setdefault(
                (network_id, hub_id), DataSet(self.config.hub_cache_cap)
            )
            cache.append((worker_id, op, payload))
            return
        hub.receive(worker_id, op, payload)

    def network_statistics(self, network_id: int) -> Optional[Statistics]:
        """Merged cross-hub statistics for one pipeline
        (StateAccumulators.scala:54-126)."""
        stats = [
            h.statistics() for (nid, _), h in self.hubs.items() if nid == network_id
        ]
        if not stats:
            return None
        merged = stats[0]
        for s in stats[1:]:
            merged = merged.merge(s)
        return merged

    def on_terminate(self) -> None:
        for hub in self.hubs.values():
            hub.on_terminate()
