"""Job-level configuration.

Mirrors the reference's flag system: Flink ``ParameterTool`` CLI flags with code
defaults (reference: src/main/scala/omldm/utils/DefaultJobParameters.scala:3-12,
src/main/scala/omldm/Job.scala:113-120, README.md:28-41). Per-pipeline
configuration arrives at runtime inside ``Request.training_configuration``
(see omldm_tpu.api.requests).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping


@dataclasses.dataclass
class JobConfig:
    """Global job configuration.

    Defaults replicate the reference's ``DefaultJobParameters``
    (DefaultJobParameters.scala:4-11): parallelism 16, maxMsgParams 2000,
    timeout 30_000 ms, testSetSize 256, test mode on.

    TPU-specific knobs (micro-batching, dtype, mesh shape) have no reference
    counterpart: the reference fits one record at a time on the JVM
    (hs_err_pid77107.log:110-111); on TPU the unit of work is a fixed-shape
    micro-batch so XLA compiles the training step once.
    """

    job_name: str = "OMLDM"
    # Number of logical workers (spokes). Reference default 16
    # (DefaultJobParameters.scala:5).
    parallelism: int = 16
    # Message-size cap in #parameters for protocol messages
    # (DefaultJobParameters.scala:6, FlinkSpoke.scala:30).
    max_msg_params: int = 2_000
    # Silence timeout (ms) after which the statistics operator fires the
    # termination probe (DefaultJobParameters.scala:10,
    # StatisticsOperator.scala:91).
    timeout_ms: int = 30_000
    # Per-worker holdout test-set size (DefaultJobParameters.scala:11).
    test_set_size: int = 256
    # Test mode: holdout sampling, poll markers, stats harness, timer-driven
    # self-termination (DefaultJobParameters.scala:9, FlinkLearning.scala:43).
    test: bool = True
    # Checkpointing (opt-in in the reference: Job.scala:120,
    # Checkpointing.scala:9-25; 5000 ms default interval).
    checkpointing: bool = False
    check_interval_ms: int = 5_000
    checkpoint_dir: str = "/tmp/omldm_tpu_checkpoints"
    # snapshots retained on disk (oldest pruned after each save); <= 0
    # keeps everything. Recovery only ever restores the latest, but a
    # couple of spares survive a torn write of the newest file.
    checkpoint_keep: int = 3

    # --- capacity limits (host-side buffering) ---
    # Spoke training-record buffer cap (SpokeLogic.scala:32).
    record_buffer_cap: int = 100_000
    # Spoke request buffer cap (SpokeLogic.scala:34).
    request_buffer_cap: int = 10_000
    # Hub pre-creation message cache cap (StateAccumulators.scala:128-146).
    hub_cache_cap: int = 20_000
    # PS model-state bucket size in #parameters (FlinkNetwork.scala:50).
    max_param_bucket_size: int = 10_000
    # Poll/progress marker cadence in #training records (FlinkSpoke.scala:83-89).
    poll_every: int = 100

    # --- lossy-channel hardening (no reference counterpart: the reference
    # rides Kafka at-least-once and hopes) ---
    # Deterministic chaos spec for the in-process hub<->spoke bridge, e.g.
    # "seed=7,drop=0.05,dup=0.05,reorder=0.1,window=4" (per-direction
    # overrides: "up.drop=...", "down.dup=..."). Corruption classes
    # "nan"/"explode" plant seeded NaNs / 1e12 norm explosions in shipped
    # parameter vectors ("poison" mutates source records, Kafka route) —
    # the model-integrity guard's fault drivers. Empty (default) = fault
    # free; the OMLDM_CHAOS env var arms it too (reaches worker
    # subprocesses). When armed, the reliable channel (sequence numbers,
    # receive windows, NACK/resync) arms itself per pipeline.
    chaos: str = ""

    # --- model integrity (omldm_tpu.guard / runtime.deadletter; no
    # reference counterpart: the reference silently drops records its
    # parsers reject, DataPointParser.scala:13-21) ---
    # Dead-letter JSONL file for malformed / validation-rejected records
    # and requests ("" = bounded in-memory quarantine only). Every entry
    # carries a reason code; the per-pipeline guard itself is armed via
    # trainingConfiguration.guard, not here.
    dead_letter_path: str = ""
    # In-memory quarantine ring size (oldest entries evict).
    dead_letter_cap: int = 10_000

    # --- multi-tenant cohort execution (runtime.cohort; no reference
    # counterpart: the reference steps every pipeline's PipelineMap entry
    # serially per record, FlinkSpoke.scala:92-107) ---
    # "off": every pipeline dispatches its own XLA programs (the exact
    # pre-cohort code path). "auto" (default): same-spec pipelines gang
    # into one stacked launch once `cohort_min` of them are live on a
    # spoke. "on": every eligible pipeline cohorts immediately.
    cohort: str = "auto"
    # homogeneous-pipeline count above which "auto" forms a cohort.
    cohort_min: int = 8
    # gang member iteration: "map" (lax.map — bit-identical to
    # per-pipeline execution, the CPU default), "vmap" (batched — faster
    # on parallel backends, ~1e-9 batched-reduction drift), or "auto"
    # (map on CPU, vmap elsewhere).
    cohort_impl: str = "auto"
    # device sharding of the cohort tenant axis (runtime.cohort): "off"
    # (default — every gang launch runs on one device, the exact
    # pre-sharding path), "auto" (lay the cohort's leading pipeline axis
    # across the largest power-of-two slice of the local mesh), or an
    # integer shard count (clamped to the local device count, floored to
    # a power of two). With S > 1 shards, members balance across shards,
    # capacity buckets are per-shard, and fit / gang predict / flat
    # params / guard health all run as ONE shard_map launch over a
    # "tenants" mesh axis with per-shard lax.map member iteration.
    cohort_shards: str = "off"
    # Hub liveness walk stride on the record path: with quorum/timeout
    # armed, the per-record check_liveness walk runs every N events (or on
    # a deadline), not per record (runtime/hub.py).
    liveness_stride: int = 16

    # --- adaptive-batching forecast serving (runtime/serving.py; no
    # reference counterpart: the reference answers every forecasting
    # record inline, FlinkSpoke.scala:92-107) ---
    # Job-wide DEFAULT serving spec applied to pipelines whose
    # trainingConfiguration carries no "serving" table of their own, e.g.
    # "maxBatch=64,maxDelayMs=5" or "relaxed" or "on". Empty (default):
    # nothing is armed and every forecast takes the exact pre-plane
    # immediate per-record predict path. Per-pipeline
    # trainingConfiguration.serving always wins (an explicit false opts a
    # pipeline out of this default).
    serving: str = ""

    # --- model lifecycle (runtime/lifecycle.py; no reference counterpart:
    # the reference's only rollout primitive is the destructive Update
    # that tears the live model down, PipelineMap.scala:43-47) ---
    # Job-wide DEFAULT lifecycle spec applied to pipelines whose
    # trainingConfiguration carries no "lifecycle" table of their own,
    # e.g. "rampTo=0.5,rampEvery=64,seed=7" or "on". Empty (default):
    # nothing is armed — zero lifecycle objects exist and every route is
    # the exact pre-plane code path. Armed, each pipeline gains a model-
    # version registry: Shadow requests register candidate configurations
    # that train + holdout-score on the live stream without serving,
    # Promote starts a deterministic hash-routed canary traffic ramp, and
    # the guard fence (candidate normLimit/non-finite trip) or a shadow-
    # score regression past scoreEnvelope auto-rolls the candidate back.
    # Per-pipeline trainingConfiguration.lifecycle always wins (an
    # explicit false opts a pipeline out).
    lifecycle: str = ""

    # --- overload control (runtime/overload.py; the reference delegates
    # overload entirely to Flink's credit-based network backpressure,
    # SURVEY §5 — the job itself has no admission control) ---
    # Job-wide DEFAULT overload spec applied to pipelines whose
    # trainingConfiguration carries no "overload" table of their own,
    # e.g. "window=64,share=2,hotHigh=48,hotCritical=160" or "on".
    # Empty (default): nothing is armed — no controller objects exist and
    # every route is the exact pre-plane code path. Armed, each spoke
    # derives a pressure level (OK/ELEVATED/CRITICAL) from its queues and
    # per-tenant admission imbalance, rate-limits tenants with
    # count-clocked token buckets, climbs a degradation ladder (widen
    # serving batching, relax staleness, defer over-limit tenants'
    # training) and finally SHEDS over-limit forecasts with reason-coded
    # dead-letter entries; the Kafka drive loops pause consumption while
    # any spoke is CRITICAL. Per-pipeline trainingConfiguration.overload
    # always wins (an explicit false opts a pipeline out).
    overload: str = ""

    # --- ingest plane (runtime/ingest_shard.py; the reference scales
    # source parallelism by adding Flink source subtasks over Kafka
    # partitions — here the analogue is N parser processes striping one
    # stream) ---
    # Sharded multi-process ingest + device-resident hot loop for file
    # runs, e.g. "shards=4,chunkKb=4096,ring=4,device=on" or "on". Empty
    # (default): nothing is armed — zero ingest objects exist and
    # StreamJob.run_file takes the exact pre-plane route (fused C ingest
    # or packed batches). Armed, N parser processes each run the fused-C
    # parse loop over a byte-grid stripe of the file and hand packed row
    # blocks to the driver through shared-memory rings; the driver
    # consumes blocks in ascending chunk order, so the fitted + holdout
    # row order is a pure function of the stream — bit-identical to
    # single-process ingest. ``device=on`` additionally moves the staging
    # pad and holdout ring onto the accelerator (SPMD pipelines; see
    # SPMDBridge.enable_resident_ingest). A dead parser process degrades
    # to in-process ingest, reason-coded through the selfheal
    # classification, instead of wedging the driver.
    ingest: str = ""

    # --- telemetry plane (runtime/telemetry.py; the reference's only
    # observability is the terminate-time JobStatistics report on the
    # performance stream, StatisticsOperator.scala:21-150) ---
    # Job-wide DEFAULT telemetry spec applied to pipelines whose
    # trainingConfiguration carries no "telemetry" table of their own,
    # e.g. "statsEvery=10000,idleMs=2000,traceSample=64" or "on". Empty
    # (default): nothing is armed — zero telemetry objects exist and
    # every route is the exact pre-plane code path. Armed, the job emits
    # continuous performance HEARTBEATS (incremental JobStatistics
    # snapshots through the on_performance sink, count-clocked every
    # statsEvery records plus a wall-clock idle tick), attributes
    # hot-loop wall time to phases (read/parse/stage/holdout/fit/serve/
    # ship), and samples 1/traceSample protocol rounds into JSONL span
    # events keyed by the transport's (networkId, seq) stamps.
    # Per-pipeline trainingConfiguration.telemetry always wins (an
    # explicit false opts a pipeline out of span sampling).
    telemetry: str = ""

    # --- flight recorder (runtime/events.py; the reference's failure
    # story is a black box: JobTerminator.scala:6-10 kills the job by
    # throwing on the first performance record, leaving no record of
    # what went wrong) ---
    # Job-wide DEFAULT events spec applied to pipelines whose
    # trainingConfiguration carries no "events" table of their own, e.g.
    # "cap=4096,watchdogEvery=10000,shedHigh=1" or "on". Empty (default):
    # nothing is armed — zero recorder objects exist and every route is
    # the exact pre-plane code path. Armed, every plane's decision sites
    # (guard trip/rollback/eviction, delta rejection + strike, quorum
    # release, resync, shed/throttle + pressure transitions, canary
    # transitions, rescale decisions, supervisor restarts) record typed
    # events into a bounded per-process journal; on guard trip, worker
    # death, rescale, or terminate the ring dumps to JSONL under
    # ``blackbox_path``; and the watchdog rule knobs (collapseFrac /
    # p99BudgetMs / shedHigh / curveSlope / silenceMs) emit ``alert``
    # events through the journal AND onto the performance sink as
    # kind="alert" records. Per-pipeline trainingConfiguration.events
    # always wins (an explicit false opts a pipeline out). NOTE: on the
    # CLI this spec rides the --flightRecorder flag — the bare --events
    # flag already names the combined replay FILE (__main__.py) and is
    # excluded from config mapping in from_args.
    events: str = ""
    # Directory for flight-recorder ring dumps (blackbox-proc<N>.jsonl)
    # and supervisor incident bundles (incident-*.json). "" (default) =
    # in-memory ring only. The events spec's own blackboxPath knob wins
    # when set; this is the job-wide CLI-friendly default
    # (--blackboxPath).
    blackbox_path: str = ""
    # In-memory prediction/response mirror cap: StreamJob keeps every
    # emitted prediction/response in a list for callers WITHOUT sink
    # callbacks; with a sink attached the list is just a mirror, so it is
    # trimmed (oldest first) beyond this many entries — a stalled/slow
    # sink consumer can no longer grow host memory with the stream.
    # <= 0 disables trimming.
    emission_buffer_cap: int = 100_000

    # --- TPU-native knobs (no reference counterpart) ---
    # Micro-batch size per training step; records are padded + masked to this
    # fixed shape so the jitted step never recompiles.
    batch_size: int = 256
    # Compute dtype for learner math. bfloat16 keeps matmuls on the MXU at
    # full rate; params are kept in float32.
    compute_dtype: str = "float32"
    # Mesh axis sizes: data-parallel spokes ("dp") and sharded parameter
    # server ("hub", the reference's HubParallelism).
    mesh_shape: Mapping[str, int] = dataclasses.field(
        default_factory=lambda: {"dp": 1, "hub": 1}
    )

    # Aliases mapping the reference's exact CLI flag names to our fields
    # (FlinkLearning.scala:43-48, Job.scala:120, Checkpointing.scala:15-22).
    _FLAG_ALIASES = {
        "timeout": "timeout_ms",
        "checkInterval": "check_interval_ms",
        "stateBackend": "checkpoint_dir",
        "jobName": "job_name",
    }

    @classmethod
    def from_args(cls, args: Mapping[str, Any]) -> "JobConfig":
        """Build a config from a flat string map (CLI-style), mirroring
        ``ParameterTool.fromArgs`` (Job.scala:114). Accepts snake_case,
        camelCase, and the reference's own flag names (e.g. ``timeout``)."""
        cfg = cls()
        args = dict(args)
        # the bare --events CLI flag names the combined replay FILE
        # (__main__.py), not the flight-recorder spec: drop it from
        # config mapping and accept the spec as --flightRecorder instead
        # (programmatic JobConfig(events=...) is unaffected)
        args.pop("events", None)
        if "flightRecorder" in args:
            args["events"] = args.pop("flightRecorder")
        for alias, field_name in cls._FLAG_ALIASES.items():
            if alias in args and field_name not in args:
                args[field_name] = args.pop(alias)
        for field in dataclasses.fields(cls):
            for key in (field.name, _camel(field.name)):
                if key in args:
                    raw = args[key]
                    current = getattr(cfg, field.name)
                    if isinstance(current, bool):
                        value = str(raw).lower() in ("1", "true", "yes", "on")
                    elif isinstance(current, int):
                        value = int(raw)
                    elif isinstance(current, str):
                        value = str(raw)
                    elif field.name == "mesh_shape" and isinstance(raw, str):
                        # "dp=8,hub=2" -> {"dp": 8, "hub": 2}
                        value = {
                            k.strip(): int(v)
                            for k, v in (p.split("=") for p in raw.split(",") if p)
                        }
                    else:
                        value = raw
                    setattr(cfg, field.name, value)
        return cfg


def _camel(snake: str) -> str:
    head, *tail = snake.split("_")
    return head + "".join(t.capitalize() for t in tail)
