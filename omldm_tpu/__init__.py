"""omldm_tpu — a TPU-native streaming online-machine-learning framework.

A from-scratch JAX/XLA/pjit/pallas re-design of the capabilities of
ArisKonidaris/OMLDM (reference mounted at /root/reference): a streaming,
distributed, online ML serving-and-training system hosting many concurrent ML
pipelines, training them with pluggable distributed-learning protocols over a
worker <-> parameter-server topology, and emitting predictions, query
responses, and training statistics back to the stream.

Where the reference runs per-record JVM learners inside Flink operators and
routes the parameter-server feedback edge through a Kafka topic
(reference: src/main/scala/omldm/Job.scala:76-87), this framework runs
``jax.jit``-compiled micro-batch learner updates on TPU HBM and performs
protocol synchronization as XLA collectives (psum / pmean / reduce_scatter /
all_gather) over the ICI mesh, with a host-side async stream runtime handling
ingest, control requests, checkpointing, and the statistics/termination
harness.

Layer map (mirrors SURVEY.md section 1):
    - ``omldm_tpu.api``           external JSON contract (ControlAPI POJOs)
    - ``omldm_tpu.learners``      online learner kernels (mlAPI learners)
    - ``omldm_tpu.preprocessors`` streaming feature transforms
    - ``omldm_tpu.pipelines``     preprocessors + learner composition
    - ``omldm_tpu.protocols``     the 8 distributed-learning protocols
    - ``omldm_tpu.parallel``      mesh / sharding / collective utilities
    - ``omldm_tpu.runtime``       host-side stream runtime (spoke/hub/job)
    - ``omldm_tpu.checkpoint``    snapshot / restore / rescale-merge
    - ``omldm_tpu.ops``           pallas kernels for hot ops (flash/ring/
                                  ulysses attention, PA scan) + native C++
    - ``omldm_tpu.models``        sequence-model family (transformer,
                                  MoE, KV-cache decode) — long-context
                                  scope beyond the reference
    - ``omldm_tpu.utils``         tracing / profiling / shared helpers
"""

__version__ = "0.1.0"

from omldm_tpu.config import JobConfig  # noqa: F401
