"""Quantization kernels for the hub<->spoke transport codec.

The GM/FGM protocols cut communication by *skipping* synchronizations;
this module cuts the cost of the synchronizations that do happen, by
shrinking every shipped parameter vector (1-bit-SGD / QSGD-lineage lossy
compression with error feedback — see PAPERS.md, communication-efficient
distributed SGD). Two families of kernels live here:

- **Host kernels** (numpy): exact affine int8, fp16 round-trips, and
  top-k delta sparsification, used by the host-plane transport codec
  (``omldm_tpu.runtime.codec``) at the message ship boundary.
- **Device kernels** (jax, jit-friendly): quantize-dequantize (QDQ)
  twins of the host kernels for the SPMD engine, applied to the vectors
  entering/leaving the protocol collectives inside the compiled step.
  They are pure elementwise/reduction ops — no ``shard_map`` or
  collective primitives of their own (anything that did need one would
  route through ``omldm_tpu.utils.jaxcompat``, never raw
  ``jax.shard_map``: the pinned jax 0.4.37 image lacks vma typing).

Error feedback is the CALLER's job (the transport codec keeps per-stream
residual accumulators; the SPMD step keeps an ``ef`` state leaf): the
kernels here are stateless and deterministic, so sender-side encode and
receiver-side decode of the same bytes always agree.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# wire bytes per parameter element, by codec kind (the int8 affine meta —
# scale + zero point, two float32 — is accounted per LEAF, not per element)
BYTES_PER_ELEMENT = {"none": 4.0, "fp16": 2.0, "int8": 1.0}
# per-leaf metadata bytes on the wire (shape/dtype ride in the in-process
# object header, matching how payload_size counts raw ndarrays: buffer only)
LEAF_META_BYTES = {"none": 0, "fp16": 0, "int8": 8}


# --- host kernels (numpy) ---


def fp16_encode(x: np.ndarray) -> np.ndarray:
    """Lossy fp32 -> fp16 cast (2 bytes/element on the wire)."""
    return np.asarray(x, np.float16)


def fp16_decode(q: np.ndarray, dtype=np.float32) -> np.ndarray:
    return np.asarray(q, dtype)


def int8_affine_encode(
    x: np.ndarray,
) -> Tuple[np.ndarray, np.float32, np.float32]:
    """Per-leaf affine (asymmetric) quantization to uint8.

    ``q = round((x - zero) / scale)`` with ``zero = min(x)`` and
    ``scale = (max(x) - min(x)) / 255`` — 1 byte/element + 8 bytes of
    (scale, zero) metadata. Returns ``(q, scale, zero)``.
    """
    x = np.asarray(x, np.float32)
    if x.size == 0:
        return x.astype(np.uint8), np.float32(1.0), np.float32(0.0)
    lo = np.float32(x.min())
    hi = np.float32(x.max())
    if not (np.isfinite(lo) and np.isfinite(hi)):
        # FAIL LOUDLY: quantizing a non-finite leaf would silently encode
        # garbage (NaN -> rint -> undefined uint8) and ship it as a
        # plausible-looking model. Non-finite state is a sender-side
        # corruption the model-integrity guard exists to catch BEFORE the
        # ship boundary; the codec must never launder it.
        raise ValueError(
            "int8 codec: non-finite values in leaf "
            f"(min={x.min()!r}, max={x.max()!r}); refusing to encode"
        )
    scale = np.float32((hi - lo) / 255.0)
    if not np.isfinite(scale) or scale <= 0:
        # degenerate range (constant/zero leaf, or a subnormal span whose
        # /255 underflows): scale 1 with zero-point ``lo`` encodes every
        # element as q=0 -> decode == lo exactly — a lossless passthrough
        # that leaves NO error-feedback residual behind
        scale = np.float32(1.0)
    q = np.clip(np.rint((x - lo) / scale), 0, 255).astype(np.uint8)
    return q, scale, lo


def int8_affine_decode(
    q: np.ndarray, scale: float, zero: float, dtype=np.float32
) -> np.ndarray:
    return (np.asarray(q, np.float32) * np.float32(scale) + np.float32(zero)).astype(
        dtype
    )


def int8_quantization_step(x: np.ndarray) -> float:
    """The affine grid step for ``x`` — the per-element round-trip error
    bound (|decode(encode(x)) - x| <= step/2 elementwise... the clip at
    the range ends makes the bound exactly one full step)."""
    x = np.asarray(x, np.float32)
    if x.size == 0:
        return 0.0
    return max(float(x.max() - x.min()) / 255.0, 0.0)


def topk_encode(
    delta: np.ndarray, k: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k magnitude sparsification of a (flat) delta vector.

    Returns ``(idx int32, val float32)`` of the k largest-|.| entries
    (8 bytes/kept element on the wire). The dropped mass is the caller's
    error-feedback residual — it ships on a later sync."""
    flat = np.asarray(delta, np.float32).ravel()
    k = max(min(int(k), flat.size), 0)
    if k == 0:
        return np.zeros((0,), np.int32), np.zeros((0,), np.float32)
    if k >= flat.size:
        idx = np.arange(flat.size, dtype=np.int32)
        return idx, flat.copy()
    part = np.argpartition(np.abs(flat), flat.size - k)[flat.size - k:]
    idx = np.sort(part).astype(np.int32)
    return idx, flat[idx]


def topk_decode(
    idx: np.ndarray, val: np.ndarray, size: int, dtype=np.float32
) -> np.ndarray:
    """Scatter a top-k (idx, val) delta back into a dense flat vector."""
    out = np.zeros((int(size),), dtype)
    out[np.asarray(idx, np.int64)] = np.asarray(val, dtype)
    return out


# --- device kernels (jax; QDQ = quantize-dequantize at the ship boundary) ---


def qdq_fp16(x):
    """fp32 -> fp16 -> fp32 round-trip, jit-friendly: the values that
    cross the (emulated) wire are exactly fp16-representable."""
    import jax.numpy as jnp

    return x.astype(jnp.float16).astype(jnp.float32)


def qdq_int8(x):
    """Symmetric per-vector int8 QDQ: ``scale = max|x| / 127``,
    ``q = clip(round(x / scale))``, returns ``q * scale``. Symmetric (no
    zero point) keeps the kernel a pure map-reduce — the natural form
    inside a compiled collective step; the host codec's affine variant
    buys ~1 bit of extra precision on skewed leaves at the cost of
    per-leaf metadata."""
    import jax.numpy as jnp

    amax = jnp.max(jnp.abs(x))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0)
    return q * scale


def make_qdq(kind: str):
    """The device QDQ kernel for a codec kind (None for ``none``)."""
    if kind in (None, "none"):
        return None
    if kind == "fp16":
        return qdq_fp16
    if kind == "int8":
        return qdq_int8
    raise ValueError(
        f"no device QDQ kernel for codec {kind!r} (topk is a host-plane "
        "transport codec: the collective engine's allreduce needs dense "
        "operands)"
    )
