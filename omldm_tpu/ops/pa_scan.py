"""Pallas kernel: fused per-record Passive-Aggressive scan.

The exact per-record PA update is inherently sequential (each projection
depends on the previous weights), which the reference runs one JVM call per
record (MLPipeline.pipePoint, hs_err_pid77107.log:111) and the generic JAX
path runs as ``lax.scan`` over per-record dots — correct, but each scan step
is a tiny HLO loop iteration. This kernel keeps the weight vector in VMEM
and sweeps the whole micro-batch in one pallas program: one HBM read for the
batch, one weight write-back, no per-step dispatch.

Used by ``PAClassifier.update_per_record`` when ``usePallas`` is set in the
learner hyper-parameters (and transparently in interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# lane width of the TPU vector unit; feature dim is padded to a multiple
LANE = 128


def _pa_kernel(x_ref, y_ref, m_ref, w0_ref, w_out_ref, loss_ref, *, variant: str, C: float):
    B = x_ref.shape[0]

    def body(i, carry):
        w, acc = carry
        x = x_ref[i, :]
        ys = jnp.where(y_ref[i, 0] > 0.0, 1.0, -1.0)
        margin = jnp.sum(w * x)
        hinge = jnp.maximum(0.0, 1.0 - ys * margin)
        sq = jnp.maximum(jnp.sum(x * x), 1e-12)
        if variant == "PA":
            tau = hinge / sq
        elif variant == "PA-I":
            tau = jnp.minimum(C, hinge / sq)
        else:  # PA-II
            tau = hinge / (sq + 1.0 / (2.0 * C))
        m = m_ref[i, 0]
        return w + (tau * ys * m) * x, acc + hinge * m

    w, loss_sum = jax.lax.fori_loop(0, B, body, (w0_ref[:], jnp.float32(0.0)))
    w_out_ref[:] = w
    # TPU VMEM stores must be vector-shaped: broadcast the scalar loss sum
    loss_ref[:] = jnp.full((LANE,), loss_sum, jnp.float32)


@functools.partial(jax.jit, static_argnames=("variant", "C", "interpret"))
def pa_scan_update(w, x, y, mask, variant: str = "PA-I", C: float = 0.01,
                   interpret: bool = False):
    """Exact sequential PA pass over a micro-batch.

    w[D], x[B, D], y[B], mask[B] -> (new_w[D], mean_loss). Pads D to the
    TPU lane width; padding columns carry zeros and do not affect the math."""
    B, D = x.shape
    pad = (-D) % LANE
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
        w = jnp.pad(w, (0, pad))
    y2 = y.reshape(B, 1)
    m2 = mask.reshape(B, 1)
    new_w, loss_vec = pl.pallas_call(
        functools.partial(_pa_kernel, variant=variant, C=float(C)),
        out_shape=(
            jax.ShapeDtypeStruct((D + pad,), jnp.float32),
            jax.ShapeDtypeStruct((LANE,), jnp.float32),
        ),
        interpret=interpret,
    )(x.astype(jnp.float32), y2.astype(jnp.float32), m2.astype(jnp.float32),
      w.astype(jnp.float32))
    total = jnp.maximum(jnp.sum(mask), 1.0)
    return new_w[:D], loss_vec[0] / total
