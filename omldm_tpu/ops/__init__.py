"""Custom ops: pallas kernels + native host components."""
