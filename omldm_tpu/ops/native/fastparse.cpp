// Native streaming-record parser: JSON lines -> packed feature arrays.
//
// TPU-native equivalent of the reference's ingest hot path
// (DataInstanceParser + DataPointParser, reference:
// src/main/scala/omldm/utils/parsers/*): the JVM parses each record with Jackson
// into POJOs; here a single C++ pass over the byte buffer extracts the
// schema-known fields (numericalFeatures, discreteFeatures, target,
// operation) straight into packed float32 batch arrays, skipping Python
// object churn entirely. Records that do not match the fast schema are
// flagged so the caller can fall back to the Python parser (identical
// drop/keep semantics).
//
// Build: g++ -O3 -shared -fPIC -o libfastparse.so fastparse.cpp
//
// Exposed C ABI:
//   int omldm_parse_lines(buf, len, dim, max_records, x, y, op, valid)
// Returns the number of lines consumed. For line i:
//   valid[i] = 1 parsed ok, 0 dropped (invalid/EOS), 2 needs Python fallback
//   op[i]    = 0 training, 1 forecasting
//   y[i]     = target (0 when absent); x[i*dim .. i*dim+dim) zero-padded.

#include <cctype>
#include <cmath>
#include <cstdlib>
#include <cstring>

namespace {

struct Cursor {
  const char* p;
  const char* end;
};

inline void skip_ws(Cursor& c) {
  while (c.p < c.end && (*c.p == ' ' || *c.p == '\t')) ++c.p;
}

// Parse a JSON number at the cursor; returns false on malformed input.
inline bool parse_number(Cursor& c, double* out) {
  char* endp = nullptr;
  double v = strtod(c.p, &endp);
  if (endp == c.p || endp > c.end) return false;
  if (!std::isfinite(v)) return false;  // NaN/Infinity are rejected (parity
                                        // with DataInstance.is_valid)
  c.p = endp;
  *out = v;
  return true;
}

// Parse a JSON array of numbers into dst (cap n); *count <- #parsed.
// Cursor must sit on '['. Non-numeric elements => false (fallback).
inline bool parse_num_array(Cursor& c, float* dst, int cap, int* count) {
  if (c.p >= c.end || *c.p != '[') return false;
  ++c.p;
  int n = 0;
  skip_ws(c);
  if (c.p < c.end && *c.p == ']') {
    ++c.p;
    *count = 0;
    return true;
  }
  while (c.p < c.end) {
    skip_ws(c);
    double v;
    if (!parse_number(c, &v)) return false;
    if (n < cap) dst[n] = static_cast<float>(v);
    ++n;
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') {
      ++c.p;
      continue;
    }
    if (c.p < c.end && *c.p == ']') {
      ++c.p;
      *count = (n < cap) ? n : cap;
      return true;
    }
    return false;
  }
  return false;
}

// Find `"key"` at the top level of the line (naive scan is fine: the schema
// has no nested objects with clashing keys except inside "metadata", which
// triggers fallback below). Returns pointer past the ':' or nullptr.
inline const char* find_key(const char* line, const char* end, const char* key) {
  size_t klen = strlen(key);
  for (const char* p = line; p + klen + 3 < end; ++p) {
    if (*p == '"' && strncmp(p + 1, key, klen) == 0 && p[klen + 1] == '"') {
      const char* q = p + klen + 2;
      while (q < end && (*q == ' ' || *q == '\t')) ++q;
      if (q < end && *q == ':') return q + 1;
    }
  }
  return nullptr;
}

}  // namespace

extern "C" {

int omldm_parse_lines(const char* buf, long len, int dim, int max_records,
                      float* x, float* y, unsigned char* op,
                      unsigned char* valid) {
  const char* p = buf;
  const char* bufend = buf + len;
  int i = 0;
  while (p < bufend && i < max_records) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', bufend - p));
    const char* line_end = nl ? nl : bufend;

    float* xi = x + static_cast<long>(i) * dim;
    memset(xi, 0, sizeof(float) * dim);
    y[i] = 0.0f;
    op[i] = 0;
    valid[i] = 0;

    // default outcome computed below; blank lines / EOS markers drop
    const char* q = p;
    while (q < line_end && isspace(static_cast<unsigned char>(*q))) ++q;
    long ll = line_end - q;
    bool blank = (ll == 0);
    bool eos = (ll == 3 && strncmp(q, "EOS", 3) == 0) ||
               (ll == 5 && strncmp(q, "\"EOS\"", 5) == 0);
    if (!blank && !eos) {
      // categorical features / metadata need the Python path (hashing,
      // arbitrary nesting)
      if (find_key(q, line_end, "categoricalFeatures") ||
          find_key(q, line_end, "metadata")) {
        valid[i] = 2;
      } else {
        int pos = 0;
        bool ok = true, any = false;
        const char* v = find_key(q, line_end, "numericalFeatures");
        if (v) {
          Cursor c{v, line_end};
          skip_ws(c);
          int cnt = 0;
          if (parse_num_array(c, xi, dim, &cnt)) {
            pos = cnt;
            any = any || cnt > 0;
          } else {
            ok = false;
          }
        }
        v = ok ? find_key(q, line_end, "discreteFeatures") : nullptr;
        if (v) {
          Cursor c{v, line_end};
          skip_ws(c);
          int cnt = 0;
          if (parse_num_array(c, xi + pos, dim - pos, &cnt)) {
            any = any || cnt > 0;
          } else {
            ok = false;
          }
        }
        v = ok ? find_key(q, line_end, "target") : nullptr;
        if (v) {
          Cursor c{v, line_end};
          skip_ws(c);
          double t;
          if (parse_number(c, &t)) {
            y[i] = static_cast<float>(t);
          } else {
            ok = false;  // non-numeric target: Jackson-parity drop
            any = false;
          }
        }
        v = find_key(q, line_end, "operation");
        if (v) {
          Cursor c{v, line_end};
          skip_ws(c);
          if (c.p + 9 <= line_end && strncmp(c.p, "\"forecast", 9) == 0) {
            op[i] = 1;
          } else if (c.p + 9 <= line_end && strncmp(c.p, "\"training", 9) == 0) {
            op[i] = 0;
          } else {
            any = false;  // unknown operation: drop
          }
        }
        valid[i] = (ok && any) ? 1 : 0;
      }
    }
    ++i;
    p = nl ? nl + 1 : bufend;
  }
  return i;
}

}  // extern "C"
