// Native streaming-record parser: JSON lines -> packed feature arrays.
//
// TPU-native equivalent of the reference's ingest hot path
// (DataInstanceParser + DataPointParser, reference:
// src/main/scala/omldm/utils/parsers/*): the JVM parses each record with Jackson
// into POJOs; here a single C++ pass over the byte buffer extracts the
// schema-known fields (numericalFeatures, discreteFeatures, target,
// operation) straight into packed float32 batch arrays, skipping Python
// object churn entirely. Records that do not match the fast schema are
// flagged so the caller can fall back to the Python parser (identical
// drop/keep semantics).
//
// Build: g++ -O3 -shared -fPIC -pthread -o libfastparse.so fastparse.cpp
//
// Exposed C ABI:
//   int omldm_parse_lines(buf, len, dim, max_records, x, y, op, valid,
//                         bytes_consumed)
//   int omldm_parse_lines_mt(buf, len, dim, max_records, x, y, op, valid,
//                            n_threads, bytes_consumed)
// Returns the number of lines consumed and stores the byte offset consumed
// (so a caller sizing its arrays by estimate can continue from there
// without pre-counting newlines). For line i:
//   valid[i] = 1 parsed ok, 0 dropped (invalid/EOS), 2 needs Python fallback
//   op[i]    = 0 training, 1 forecasting
//   y[i]     = target (0 when absent); x[i*dim .. i*dim+dim) zero-padded.
//
// Throughput design (this is the part that keeps a TPU chip fed):
// - ONE structural walk per line (key -> value, values skipped with memchr)
//   instead of re-scanning the line for every known key;
// - SWAR digit parsing: 8 or 4 ASCII digits converted per multiply chain
//   (the classic 0x0F0F... mask + pairwise-merge trick) instead of a serial
//   mant = mant*10 + d chain; strtod only for oddball syntax;
// - the _mt entry indexes newline offsets then parses disjoint line ranges
//   on std::threads (each line owns its output row; nothing is shared).

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

const double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                         1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                         1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// --- SWAR digit runs -------------------------------------------------------

const uint64_t kPow10u[] = {1ull,       10ull,       100ull,
                            1000ull,    10000ull,    100000ull,
                            1000000ull, 10000000ull, 100000000ull};

// 8 ASCII digits -> value (Lemire's parse_eight_digits).
inline uint64_t swar8(uint64_t c) {
  c -= 0x3030303030303030ull;
  c = (c * 10) + (c >> 8);
  const uint64_t mask = 0x000000FF000000FFull;
  const uint64_t mul1 = 0x000F424000000064ull;  // 100 + (1000000 << 32)
  const uint64_t mul2 = 0x0000271000000001ull;  // 1 + (10000 << 32)
  c = (((c & mask) * mul1) + (((c >> 16) & mask) * mul2)) >> 32;
  return c;
}

// Count the leading ASCII-digit bytes of an 8-byte (text-order) load: a
// byte is a digit iff (c^0x30) <= 9; the +0x76 carry trick sets the high
// bit of every non-digit byte, ctz finds the first one.
inline int digit_prefix_len8(uint64_t c8) {
  uint64_t t = c8 ^ 0x3030303030303030ull;
  uint64_t nd = ((t + 0x7676767676767676ull) | t) & 0x8080808080808080ull;
  if (nd == 0) return 8;
  return static_cast<int>(__builtin_ctzll(nd)) >> 3;
}

// Accumulate a digit run into mant; returns #digits consumed. One 8-byte
// load classifies the run head (no all-or-nothing retries): a partial run
// of n digits is shifted to the tail bytes, the head refilled with ASCII
// zeros, and folded with the same swar8.
inline int parse_digit_run(const char*& p, const char* end, uint64_t& mant) {
  int digits = 0;
  while (end - p >= 8) {
    uint64_t c8;
    memcpy(&c8, p, 8);
    int nd = digit_prefix_len8(c8);
    if (nd == 8) {
      mant = mant * 100000000ull + swar8(c8);
      digits += 8;
      p += 8;
      continue;
    }
    if (nd > 0) {
      int s = 8 * (8 - nd);  // s in [8, 56]: both shifts below are defined
      uint64_t shifted =
          (c8 << s) | (0x3030303030303030ull >> (64 - s));
      mant = mant * kPow10u[nd] + swar8(shifted);
      digits += nd;
      p += nd;
    }
    return digits;
  }
  while (p < end && *p >= '0' && *p <= '9') {
    mant = mant * 10ull + static_cast<uint64_t>(*p - '0');
    ++digits;
    ++p;
  }
  return digits;
}

// float32 boundary clamp, identical to the Python side's
// runtime/vectorizer.clamp_f32: finite doubles beyond float32 range store
// as +/-FLT_MAX instead of overflowing to inf (inf would poison device
// state); parity pinned by tests/test_parser_fuzz.py.
inline float to_f32_clamped(double v) {
  if (v > 3.4028234663852886e38) return 3.4028234663852886e38f;
  if (v < -3.4028234663852886e38) return -3.4028234663852886e38f;
  return static_cast<float>(v);
}

struct Cursor {
  const char* p;
  const char* end;
};

inline void skip_ws(Cursor& c) {
  // JSON's own whitespace set (what json.loads allows BETWEEN tokens)
  while (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\n' ||
                         *c.p == '\r'))
    ++c.p;
}

// Python str.strip() whitespace (ASCII subset): what the codec strips off
// the EDGES of a line before json.loads (DataInstance.from_json)
inline bool is_edge_ws(char ch) {
  return ch == ' ' || ch == '\t' || ch == '\r' || ch == '\f' ||
         ch == '\v' || (ch >= '\x1c' && ch <= '\x1f');
}

// JSON-number parse: [-]digits[.digits][e[±]dd]. Falls back to strtod when
// the mantissa exceeds 19 digits or the syntax is unusual; rejects
// NaN/Infinity (parity with DataInstance.is_valid).
inline bool parse_number(Cursor& c, double* out) {
  const char* p = c.p;
  const char* end = c.end;
  if (p >= end) return false;
  // branchless sign consume: random signs in numeric streams would
  // mispredict a conditional ++p roughly every other number. A leading
  // '+' stays invalid (json.loads parity): it fails the digit check below.
  bool neg = (*p == '-');
  p += neg;
  // strict JSON grammar: the integer part needs >= 1 digit and no
  // leading zero — ".5", "-.5", "01", "+1" are json.loads drops. The
  // next-byte load is guarded by a (predictable) bounds branch; the digit
  // compares stay branchless ('0' leads ~half of sub-1 magnitudes).
  if (p >= end || *p < '0' || *p > '9') return false;
  char c1 = (p + 1 < end) ? p[1] : '\0';
  if ((*p == '0') & (c1 >= '0') & (c1 <= '9')) return false;
  uint64_t mant = 0;
  int digits = 0;
  int frac = 0;
  // One-window fast path for the dominant shape "d.f{1..6}" (one integer
  // digit, '.' and up to six fraction digits all inside one 8-byte load):
  // classifies the window once instead of two digit-run calls.
  if (end - p >= 8) {
    uint64_t c8;
    memcpy(&c8, p, 8);
    uint64_t t = c8 ^ 0x3030303030303030ull;
    uint64_t nd = ((t + 0x7676767676767676ull) | t) & 0x8080808080808080ull;
    if ((nd & 0x000000000000FF00ull) && !(nd & 0xFFull) &&
        ((c8 >> 8) & 0xFFull) == '.') {
      uint64_t rest = nd >> 16;  // non-digits among fraction bytes 2..7
      int fr = rest ? static_cast<int>(__builtin_ctzll(rest)) >> 3 : 6;
      bool full_window = (fr == 6);
      // a full window might truncate a longer fraction: only take the fast
      // path when the byte after the window cannot extend the number.
      // fr == 0 ("1.,") falls through to the slow path, which rejects a
      // dot with no fraction digits (json.loads parity).
      if (fr > 0)
      if (!full_window ||
          (end - p > 8 && !(p[8] >= '0' && p[8] <= '9') && p[8] != '.') ||
          end - p == 8) {
        uint64_t d0 = c8 & 0x0Full;
        if (fr > 0) {
          int s = 8 * (8 - fr);
          uint64_t shifted =
              ((c8 >> 16) << s) | (0x3030303030303030ull >> (64 - s));
          mant = d0 * kPow10u[fr] + swar8(shifted);
        } else {
          mant = d0;
        }
        digits = 1 + fr;
        frac = fr;
        p += 2 + fr;
        goto have_mantissa;
      }
    }
  }
  digits = parse_digit_run(p, end, mant);
  frac = 0;
  if (p < end && *p == '.') {
    ++p;
    frac = parse_digit_run(p, end, mant);
    if (frac == 0) return false;  // "1." is a json.loads drop
    digits += frac;
  }
have_mantissa:;
  if (digits == 0 || digits > 19) {
    // empty ("-", ".") or precision/overflow-risky: defer to strtod
    char* endp = nullptr;
    double v = strtod(c.p, &endp);
    if (endp == c.p || endp > c.end) return false;
    if (!std::isfinite(v)) return false;
    c.p = endp;
    *out = v;
    return true;
  }
  int exp10 = -frac;
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      ++p;
    }
    int e = 0, edigs = 0;
    while (p < end && *p >= '0' && *p <= '9' && edigs < 6) {
      e = e * 10 + (*p - '0');
      ++edigs;
      ++p;
    }
    if (edigs == 0) return false;
    exp10 += eneg ? -e : e;
  }
  double v = static_cast<double>(mant);
  if (exp10 > 0) {
    v = (exp10 > 22) ? v * std::pow(10.0, exp10) : v * kPow10[exp10];
  } else if (exp10 < 0) {
    v = (exp10 < -22) ? v / std::pow(10.0, -exp10) : v / kPow10[-exp10];
  }
  if (!std::isfinite(v)) return false;
  c.p = p;
  // branchless sign application (same misprediction argument as above)
  uint64_t vb;
  memcpy(&vb, &v, 8);
  vb ^= static_cast<uint64_t>(neg) << 63;
  memcpy(out, &vb, 8);
  return true;
}

// Parse a JSON array of numbers into dst (cap n); *count <- #parsed.
// Cursor must sit on '['. Non-numeric elements => false (fallback).
// The element loop is specialized for the dominant separators — "', '"
// between elements, none around the brackets — with a full skip_ws
// fallback for any other JSON whitespace arrangement.
inline bool parse_num_array(Cursor& c, float* dst, int cap, int* count) {
  if (c.p >= c.end || *c.p != '[') return false;
  ++c.p;
  int n = 0;
  skip_ws(c);
  if (c.p < c.end && *c.p == ']') {
    ++c.p;
    *count = 0;
    return true;
  }
  // Fast lane for the dominant serialized-float shape: "[-]d.dddddd"
  // elements separated by "', '" (what %.6f streams emit). The win over
  // parse_number is the pointer-advance chain: the next element's start
  // depends only on the sign byte (fixed width otherwise), not on the
  // digit-run classify (ctz) of the current one, so the CPU overlaps
  // several elements' parses. Bit-identical math to the one-window fast
  // path (same mant construction, same kPow10 divide); any other shape
  // falls through to the general loop with the element unconsumed.
  while (c.end - c.p >= 11) {
    const char* e = c.p;
    bool eneg = (*e == '-');
    e += eneg;
    uint64_t c8;
    memcpy(&c8, e, 8);
    uint64_t t = c8 ^ 0x3030303030303030ull;
    uint64_t ndm = ((t + 0x7676767676767676ull) | t) & 0x8080808080808080ull;
    // exactly byte 1 non-digit (and it must be '.'): d . d d d d d d
    if (ndm != 0x8000ull || ((c8 >> 8) & 0xFFull) != '.') break;
    char sep = e[8];
    if (sep != ',' && sep != ']') break;  // longer fraction / exp / ws
    uint64_t d0 = c8 & 0x0Full;
    uint64_t shifted = ((c8 >> 16) << 16) | (0x3030303030303030ull >> 48);
    uint64_t mant = d0 * kPow10u[6] + swar8(shifted);
    double v = static_cast<double>(mant) / kPow10[6];
    uint64_t vb;
    memcpy(&vb, &v, 8);
    vb ^= static_cast<uint64_t>(eneg) << 63;
    memcpy(&v, &vb, 8);
    if (n < cap) dst[n] = to_f32_clamped(v);
    ++n;
    if (sep == ']') {
      c.p = e + 9;
      *count = (n < cap) ? n : cap;
      return true;
    }
    c.p = e + 9;
    if (c.p < c.end && *c.p == ' ') ++c.p;
    if (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\n' ||
                        *c.p == '\r'))
      skip_ws(c);
  }
  while (c.p < c.end) {
    double v;
    if (!parse_number(c, &v)) return false;
    if (n < cap) dst[n] = to_f32_clamped(v);
    ++n;
    if (c.p >= c.end) return false;
    char ch = *c.p;
    if (ch == ',') {
      ++c.p;
      if (c.p < c.end && *c.p == ' ') ++c.p;
      if (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\n' ||
                          *c.p == '\r'))
        skip_ws(c);
      continue;
    }
    if (ch == ']') {
      ++c.p;
      *count = (n < cap) ? n : cap;
      return true;
    }
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') {
      ++c.p;
      skip_ws(c);
      continue;
    }
    if (c.p < c.end && *c.p == ']') {
      ++c.p;
      *count = (n < cap) ? n : cap;
      return true;
    }
    return false;
  }
  return false;
}

// --- single-pass structural walk ------------------------------------------

// Known keys, matched by (length, bytes).
enum KeyId {
  KEY_NUMERICAL,
  KEY_DISCRETE,
  KEY_CATEGORICAL,
  KEY_METADATA,
  KEY_TARGET,
  KEY_OPERATION,
  KEY_UNKNOWN,
};

inline KeyId match_key(const char* k, size_t len) {
  switch (len) {
    case 17:
      if (memcmp(k, "numericalFeatures", 17) == 0) return KEY_NUMERICAL;
      break;
    case 16:
      if (memcmp(k, "discreteFeatures", 16) == 0) return KEY_DISCRETE;
      break;
    case 19:
      if (memcmp(k, "categoricalFeatures", 19) == 0) return KEY_CATEGORICAL;
      break;
    case 8:
      if (memcmp(k, "metadata", 8) == 0) return KEY_METADATA;
      break;
    case 6:
      if (memcmp(k, "target", 6) == 0) return KEY_TARGET;
      break;
    case 9:
      if (memcmp(k, "operation", 9) == 0) return KEY_OPERATION;
      break;
    default:
      break;
  }
  return KEY_UNKNOWN;
}

// Skip a string; cursor sits on the opening '"'. Handles escapes.
inline bool ishex(char h) {
  return (h >= '0' && h <= '9') || (h >= 'a' && h <= 'f') ||
         (h >= 'A' && h <= 'F');
}

// First byte in [p, end) that is a backslash or a raw control char
// (< 0x20), or ``end`` — SWAR, 8 bytes per iteration. The two classes are
// exactly what interrupts a plain JSON string span: '\\' starts an escape
// and controls must be escaped (json.loads parity).
template <bool kWithQuote>
inline const char* scan_span_impl(const char* p, const char* end) {
  while (end - p >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    // zero-byte detector on w ^ '\\' -> flags bytes equal to backslash
    uint64_t x = w ^ 0x5C5C5C5C5C5C5C5CULL;
    uint64_t hit =
        (x - 0x0101010101010101ULL) & ~x & 0x8080808080808080ULL;
    // byte < 0x20: (b - 0x20) borrows into the high bit AND b < 0x80
    hit |= (w - 0x2020202020202020ULL) & ~w & 0x8080808080808080ULL;
    if (kWithQuote) {
      uint64_t xq = w ^ 0x2222222222222222ULL;  // zero byte where '"'
      hit |= (xq - 0x0101010101010101ULL) & ~xq & 0x8080808080808080ULL;
    }
    if (hit) return p + (__builtin_ctzll(hit) >> 3);
    p += 8;
  }
  for (; p < end; ++p) {
    unsigned char ch = static_cast<unsigned char>(*p);
    if (ch == '\\' || ch < 0x20 || (kWithQuote && ch == '"')) return p;
  }
  return end;
}

// First byte in [p, end) that is a backslash or a raw control char
// (< 0x20), or ``end`` — what interrupts a plain JSON string span whose
// closing quote is already known.
inline const char* scan_special(const char* p, const char* end) {
  return scan_span_impl<false>(p, end);
}

// Same scan, additionally stopping at '"': finds the closing quote OR
// the first special byte in ONE pass (memchr-then-rescan costs two
// passes plus a library call's setup at ~10-byte category strings).
inline const char* scan_quote_or_special(const char* p, const char* end) {
  return scan_span_impl<true>(p, end);
}

// Strict-JSON string scan (json.loads parity): raw control characters
// (< 0x20) must be escaped, and only the JSON escapes \" \\ \/ \b \f \n
// \r \t \uXXXX are valid. Leaves the cursor after the closing quote.
// Fast shape: memchr to the candidate closing quote, one SWAR pass over
// the span; the per-escape state machine only runs from the first
// backslash onward (strings in this schema rarely contain any).
inline bool skip_string(Cursor& c) {
  ++c.p;  // opening quote
  while (c.p < c.end) {
    const char* q =
        static_cast<const char*>(memchr(c.p, '"', c.end - c.p));
    if (!q) return false;
    const char* s = scan_special(c.p, q);
    if (s < q && static_cast<unsigned char>(*s) < 0x20) return false;
    if (s == q) {  // clean span: q really is the closing quote
      c.p = q + 1;
      return true;
    }
    // escape at s: validate it, then rescan from after it (the escaped
    // char may itself be the quote memchr found)
    if (s + 1 >= c.end) return false;
    char e = s[1];
    if (e == '"' || e == '\\' || e == '/' || e == 'b' || e == 'f' ||
        e == 'n' || e == 'r' || e == 't') {
      c.p = s + 2;
      continue;
    }
    if (e == 'u') {
      if (s + 6 > c.end || !ishex(s[2]) || !ishex(s[3]) || !ishex(s[4]) ||
          !ishex(s[5]))
        return false;
      c.p = s + 6;
      continue;
    }
    return false;  // invalid escape: json.loads drops the line
  }
  return false;
}

// Structural skip of an array/object value: tracks bracket depth and skips
// strings properly, so unknown-key values containing ']'/'}' inside strings
// or nested containers don't derail the walk.
inline bool skip_composite(Cursor& c) {
  int depth = 0;
  while (c.p < c.end) {
    char ch = *c.p;
    if (ch == '"') {
      if (!skip_string(c)) return false;
      continue;
    }
    if (ch == '[' || ch == '{') {
      ++depth;
    } else if (ch == ']' || ch == '}') {
      --depth;
      if (depth == 0) {
        ++c.p;
        return true;
      }
      if (depth < 0) return false;
    }
    ++c.p;
  }
  return false;
}

// Strictly validate a value we do not extract (json.loads parity).
// Returns 1 valid-and-consumed, 0 invalid, 2 composite: bracket-matched
// and strings validated, but contents not fully validated — the caller
// defers such lines to the Python codec, which decides exactly.
inline int check_value(Cursor& c) {
  skip_ws(c);
  if (c.p >= c.end) return 0;
  char ch = *c.p;
  if (ch == '"') return skip_string(c) ? 1 : 0;
  if (ch == '[' || ch == '{') return skip_composite(c) ? 2 : 0;
  if (ch == 't') {
    if (c.end - c.p >= 4 && strncmp(c.p, "true", 4) == 0) {
      c.p += 4;
      return 1;
    }
    return 0;
  }
  if (ch == 'f') {
    if (c.end - c.p >= 5 && strncmp(c.p, "false", 5) == 0) {
      c.p += 5;
      return 1;
    }
    return 0;
  }
  if (ch == 'n') {
    if (c.end - c.p >= 4 && strncmp(c.p, "null", 4) == 0) {
      c.p += 4;
      return 1;
    }
    return 0;
  }
  double v;
  Cursor t{c.p, c.end};
  if (parse_number(t, &v)) {
    c.p = t.p;
    return 1;
  }
  // starts like a number but failed the strict parse: overflow to inf
  // (json.loads keeps it — and is_valid never inspects ignored keys) or
  // grammar junk (json.loads drops). Either way the Python codec is the
  // authority: defer instead of dropping a possibly-valid record.
  if (ch == '-' || (ch >= '0' && ch <= '9')) return 2;
  return 0;
}

// Parse one line into output row i. xi is only defined when *validi == 1
// (features zero-padded to dim); dropped/fallback rows leave xi
// unspecified — consumers mask them out (valid != 1) or reparse via the
// Python codec, so the zero-fill is deferred to the success path instead
// of a 112-byte memset per line.
inline void parse_one_line(const char* p, const char* line_end, int dim,
                           float* xi, float* yi, unsigned char* opi,
                           unsigned char* validi) {
  *yi = 0.0f;
  *opi = 0;
  *validi = 0;

  const char* q = p;
  while (q < line_end && is_edge_ws(*q)) ++q;
  long ll = line_end - q;
  if (ll == 0) return;                                            // blank
  if ((ll == 3 && strncmp(q, "EOS", 3) == 0) ||
      (ll == 5 && strncmp(q, "\"EOS\"", 5) == 0))
    return;                                                       // EOS
  if (*q != '{') return;                                          // garbage

  // Whole-line schema template: the dominant serialized record shape
  // {"numericalFeatures": [ ... ], "target": N, "operation": "training"}
  // short-circuits the general key walk (three key scans, match_key
  // dispatch, member-separator machinery) into three memcmps around the
  // array fast lane. Any mismatch falls through to the general walk,
  // which re-parses the line from scratch — semantics are identical, the
  // template is only a faster route for lines json.loads would accept.
  {
    static const char kHead[] = "{\"numericalFeatures\": ";
    static const char kTgt[] = ", \"target\": ";
    static const char kOp[] = ", \"operation\": \"training\"}";
    const long kHeadLen = sizeof(kHead) - 1;   // 22
    const long kTgtLen = sizeof(kTgt) - 1;     // 12
    const long kOpLen = sizeof(kOp) - 1;       // 26
    if (ll > kHeadLen + kTgtLen + kOpLen &&
        memcmp(q, kHead, kHeadLen) == 0 && q[kHeadLen] == '[') {
      Cursor t{q + kHeadLen, line_end};
      int cnt = 0;
      if (parse_num_array(t, xi, dim, &cnt) && cnt > 0 &&
          line_end - t.p >= kTgtLen && memcmp(t.p, kTgt, kTgtLen) == 0) {
        t.p += kTgtLen;
        double tv;
        if (parse_number(t, &tv) && line_end - t.p >= kOpLen &&
            memcmp(t.p, kOp, kOpLen) == 0) {
          t.p += kOpLen;
          while (t.p < line_end && is_edge_ws(*t.p)) ++t.p;
          if (t.p == line_end) {
            if (cnt < dim)
              memset(xi + cnt, 0,
                     sizeof(float) * static_cast<size_t>(dim - cnt));
            *yi = to_f32_clamped(tv);
            *opi = 0;
            *validi = 1;
            return;
          }
        }
      }
    }
  }

  Cursor c{q + 1, line_end};
  // numerical parses INLINE into xi[0..] during the walk (it always packs
  // first, DataPointParser.scala:20-33 ordering); discrete parses inline at
  // xi[num_cnt..] when numerical was already seen, else its cursor is
  // recorded and parsed after the walk. Inline parsing avoids a second
  // structural pass over the array bytes (skip_composite), which dominated
  // the per-line cost.
  Cursor disc_c{nullptr, line_end};
  bool ok = true;
  bool have_target = false, have_op = false;
  double target = 0.0;
  int op_val = -1;
  int num_cnt = -1;  // -1 = numericalFeatures not seen yet
  int disc_cnt = 0;
  bool disc_seen = false;
  bool closed = false;  // saw the object's closing '}'
  bool first = true;

  while (ok && c.p < c.end) {
    skip_ws(c);
    if (c.p < c.end && *c.p == '}') {
      ++c.p;
      closed = true;
      break;
    }
    // strict member separation (json.loads parity): exactly one comma
    // between members, none before the first or after the last
    if (!first) {
      if (c.p >= c.end || *c.p != ',') {
        ok = false;
        break;
      }
      ++c.p;
      skip_ws(c);
      if (c.p < c.end && *c.p == '}') {
        ok = false;  // trailing comma
        break;
      }
    }
    first = false;
    if (c.p >= c.end || *c.p != '"') {
      ok = false;
      break;
    }
    const char* ks = c.p + 1;
    if (!skip_string(c)) {
      ok = false;
      break;
    }
    const char* ke = c.p - 1;  // closing quote
    skip_ws(c);
    if (c.p >= c.end || *c.p != ':') {
      ok = false;
      break;
    }
    ++c.p;
    skip_ws(c);
    switch (match_key(ks, ke - ks)) {
      case KEY_CATEGORICAL:
      case KEY_METADATA:
        *validi = 2;  // python fallback (hashing / nesting)
        return;
      case KEY_NUMERICAL: {
        if (num_cnt >= 0) {
          // duplicate array key: inline packing can no longer reproduce the
          // codec's last-key-wins layout — defer the line to the Python
          // fallback, which parses it identically to DataInstance.from_json
          *validi = 2;
          return;
        }
        int cnt = 0;
        if (!parse_num_array(c, xi, dim, &cnt)) {
          ok = false;  // malformed / non-numeric array: drop
          break;
        }
        num_cnt = cnt;
        break;
      }
      case KEY_DISCRETE:
        if (disc_seen) {
          *validi = 2;  // duplicate key: Python-fallback (see above)
          return;
        }
        disc_seen = true;
        if (num_cnt >= 0) {
          int cnt = 0;
          if (!parse_num_array(c, xi + num_cnt, dim - num_cnt, &cnt)) {
            ok = false;
            break;
          }
          disc_cnt = cnt;
        } else {
          // deferred array: bracket-matched here, strictly parsed after
          // the walk by parse_num_array (non-array values fail there,
          // matching the codec's element-coercion drop)
          disc_c.p = c.p;
          if (c.p < c.end && *c.p == '[') {
            if (!skip_composite(c)) ok = false;
          } else {
            int r = check_value(c);
            if (r == 0) ok = false;
            // a valid non-array value fails parse_num_array later: drop,
            // same as the codec's per-element float() coercion
          }
        }
        break;
      case KEY_TARGET: {
        Cursor t{c.p, line_end};
        if (parse_number(t, &target)) {
          have_target = true;
          c.p = t.p;
        } else if (c.end - c.p >= 4 && strncmp(c.p, "null", 4) == 0) {
          // explicit null: the codec treats it as absent (last key wins)
          have_target = false;
          target = 0.0;
          c.p += 4;
        } else {
          // string/boolean/other: the codec's float() coercion decides
          // (float("0") keeps, float("x") drops) — defer to Python
          *validi = 2;
          return;
        }
        break;
      }
      case KEY_OPERATION: {
        have_op = true;
        op_val = -1;  // duplicate keys: last one wins, like the codec
        if (c.p < c.end && *c.p == '"') {
          const char* vs = c.p + 1;
          if (!skip_string(c)) {
            ok = false;
            break;
          }
          const char* ve = c.p - 1;
          long vl = ve - vs;
          if (memchr(vs, '\\', vl) != nullptr) {
            *validi = 2;  // escaped spelling: let Python decode+compare
            return;
          }
          // EXACT match (is_valid drops any other operation string)
          if (vl == 11 && strncmp(vs, "forecasting", 11) == 0) {
            op_val = 1;
          } else if (vl == 8 && strncmp(vs, "training", 8) == 0) {
            op_val = 0;
          }
        } else {
          int r = check_value(c);
          if (r == 0) {
            ok = false;
          } else if (r == 2) {
            *validi = 2;
            return;
          }
          // valid non-string operation: op_val stays -1 -> dropped below
        }
        break;
      }
      case KEY_UNKNOWN: {
        int r = check_value(c);
        if (r == 0) {
          ok = false;
        } else if (r == 2) {
          *validi = 2;  // composite under an unknown key: Python decides
          return;
        }
        break;
      }
    }
  }
  // strict-JSON parity with the Python codec: a truncated object (no
  // closing '}') or trailing non-whitespace after it is a drop. The tail
  // may carry anything str.strip() removes (CRLF files, formfeeds, ...).
  if (!ok || !closed) return;
  while (c.p < c.end && is_edge_ws(*c.p)) ++c.p;
  if (c.p < c.end) return;

  int pos = num_cnt > 0 ? num_cnt : 0;
  if (disc_c.p) {
    // discrete appeared before numerical in the line: parse it now so it
    // still packs after the numerical block
    int cnt = 0;
    if (parse_num_array(disc_c, xi + pos, dim - pos, &cnt)) {
      disc_cnt = cnt;
    } else {
      return;
    }
  }
  bool any = num_cnt > 0 || disc_cnt > 0;
  if (have_target) *yi = to_f32_clamped(target);
  if (have_op) {
    if (op_val < 0) return;  // unknown operation: drop
    *opi = static_cast<unsigned char>(op_val);
  }
  if (any) {
    // deferred zero-fill (see above): only the unfilled tail, only on keep
    int filled = pos + disc_cnt;
    if (filled < dim)
      memset(xi + filled, 0, sizeof(float) * static_cast<size_t>(dim - filled));
    *validi = 1;
  }
}

// --- sparse (padded-COO) line parse --------------------------------------
//
// The sparse twin of parse_one_line: dense numerical/discrete values keep
// their positional slots (only nonzero values occupy a COO slot, exactly
// like SparseVectorizer.vectorize), categorical strings hash with
// zlib-CRC32 of "{i}={cat}" into [dense_budget, dense_budget + hash_space)
// with the same sign rule. Lines whose category strings contain escapes
// (the hash must cover the DECODED bytes) defer to the Python codec.

// slice-by-8 CRC-32 (zlib polynomial): 8 bytes per iteration through 8
// derived tables — category hashing is a large share of the sparse parse
struct Crc8Tables {
  uint32_t t[8][256];
  Crc8Tables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1u) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
      t[0][i] = c;
    }
    for (int s = 1; s < 8; ++s)
      for (uint32_t i = 0; i < 256; ++i)
        t[s][i] = t[0][t[s - 1][i] & 0xFFu] ^ (t[s - 1][i] >> 8);
  }
};

static const Crc8Tables CRC_T;  // namespace scope: no per-call init guard

inline uint32_t crc32_zlib(const char* data, size_t len, uint32_t seed) {
  const Crc8Tables& T = CRC_T;
  const uint32_t* t0 = T.t[0];
  uint32_t c = seed ^ 0xFFFFFFFFu;
  while (len >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= c;
    c = T.t[7][lo & 0xFFu] ^ T.t[6][(lo >> 8) & 0xFFu] ^
        T.t[5][(lo >> 16) & 0xFFu] ^ T.t[4][lo >> 24] ^
        T.t[3][hi & 0xFFu] ^ T.t[2][(hi >> 8) & 0xFFu] ^
        T.t[1][(hi >> 16) & 0xFFu] ^ T.t[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  for (size_t i = 0; i < len; ++i)
    c = t0[(c ^ static_cast<unsigned char>(data[i])) & 0xFFu] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// Exact x % d via Lemire's fastmod (two multiplies instead of a
// hardware divide); d is fixed for a whole parse call.
struct FastMod {
  uint64_t m;
  uint32_t d;
  explicit FastMod(uint32_t d_) : m(~0ULL / d_ + 1), d(d_) {}
  inline uint32_t mod(uint32_t x) const {
    uint64_t low = m * x;
    return static_cast<uint32_t>(
        (static_cast<unsigned __int128>(low) * d) >> 64);
  }
};

// Categorical string items (cursor just past '['): hash each plain
// string into a COO slot. Returns 0 ok (cursor past ']'), 1 malformed
// (json.loads drops the line), 2 Python fallback (escapes). Shared by
// the general key walk and the whole-line schema template.
inline int parse_cat_items(Cursor& c, int dense_budget,
                           const FastMod& hash_mod, int max_nnz,
                           int32_t* ii, float* vv, int& k, bool& any) {
  skip_ws(c);
  long cat_i = 0;
  if (c.p < c.end && *c.p == ']') { ++c.p; return 0; }
  while (c.p < c.end) {
    if (*c.p != '"') return 2;  // non-string element
    const char* vs = c.p + 1;
    const char* ve = scan_quote_or_special(vs, c.end);
    if (ve >= c.end) return 1;  // unterminated
    if (*ve != '"') {
      if (*ve == '\\') return 2;  // escaped content: Python decodes
      return 1;  // raw control char: json.loads drops the line
    }
    c.p = ve + 1;
    if (k < max_nnz) {
      // CRC state after the "{i}=" prefix depends only on i: cache it
      // (the prefixes repeat every line). snprintf here once measured
      // ~5 us/line; the hand-rolled digits remain for the uncached tail
      uint32_t h;
      static thread_local uint32_t prefix_crc[64];
      static thread_local bool prefix_have[64];
      if (cat_i < 64 && prefix_have[cat_i]) {
        h = prefix_crc[cat_i];
      } else {
        char prefix[24];
        int plen = 0;
        char tmp[20];
        int tl = 0;
        long t = cat_i;
        do {
          tmp[tl++] = static_cast<char>('0' + (t % 10));
          t /= 10;
        } while (t);
        while (tl) prefix[plen++] = tmp[--tl];
        prefix[plen++] = '=';
        h = crc32_zlib(prefix, plen, 0);
        if (cat_i < 64) {
          prefix_crc[cat_i] = h;
          prefix_have[cat_i] = true;
        }
      }
      h = crc32_zlib(vs, ve - vs, h);
      ii[k] = static_cast<int32_t>(dense_budget + hash_mod.mod(h));
      vv[k] = ((h >> 1) & 1u) == 0 ? 1.0f : -1.0f;
      ++k;
    }
    any = true;  // presence (even past the max_nnz cap)
    ++cat_i;
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') { ++c.p; skip_ws(c); continue; }
    if (c.p < c.end && *c.p == ']') { ++c.p; return 0; }
    return 1;
  }
  return 1;
}

// Numeric array items into COO slots (cursor just past '['): nonzero
// values at positions < dense_budget take slots; the positional cursor
// advances regardless. Returns 0 ok, 1 malformed. Shared by the general
// walk and the schema template.
inline int parse_num_items_coo(Cursor& c, int dense_budget, int max_nnz,
                               int32_t* ii, float* vv, int& k, long& pos,
                               bool& any) {
  skip_ws(c);
  if (c.p < c.end && *c.p == ']') { ++c.p; return 0; }
  while (c.p < c.end) {
    double v;
    if (!parse_number(c, &v)) return 1;
    any = true;  // validity = feature PRESENCE (is_valid counts the
                 // raw lists), not whether a nonzero slot was stored
    if (pos < dense_budget && v != 0.0 && k < max_nnz) {
      ii[k] = static_cast<int32_t>(pos);
      vv[k] = to_f32_clamped(v);
      ++k;
    }
    if (pos < dense_budget) ++pos;
    if (c.p >= c.end) return 1;
    char ch = *c.p;
    if (ch == ',') {
      ++c.p;
      if (c.p < c.end && *c.p == ' ') ++c.p;
      skip_ws(c);
      continue;
    }
    if (ch == ']') { ++c.p; return 0; }
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') { ++c.p; skip_ws(c); continue; }
    if (c.p < c.end && *c.p == ']') { ++c.p; return 0; }
    return 1;
  }
  return 1;
}

// Parse one line into padded-COO row i. Same valid semantics as
// parse_one_line (0 drop, 1 keep, 2 Python fallback).
inline void parse_one_line_sparse(const char* p, const char* line_end,
                                  int dense_budget, long hash_space,
                                  const FastMod& hash_mod,
                                  int max_nnz, int32_t* ii, float* vv,
                                  float* yi, unsigned char* opi,
                                  unsigned char* validi) {
  *yi = 0.0f;
  *opi = 0;
  *validi = 0;

  const char* q = p;
  while (q < line_end && is_edge_ws(*q)) ++q;
  long ll = line_end - q;
  if (ll == 0) return;
  if ((ll == 3 && strncmp(q, "EOS", 3) == 0) ||
      (ll == 5 && strncmp(q, "\"EOS\"", 5) == 0))
    return;
  if (*q != '{') return;

  // Whole-line schema template: the dominant sparse record shape
  // {"numericalFeatures": [..], "categoricalFeatures": [..],
  //  "target": N, "operation": "training"} short-circuits the key walk
  // (four key scans + member machinery) into four memcmps around the
  // shared item loops. Any mismatch falls through to the general walk,
  // which re-parses from scratch (ii/vv scribbles are only read when
  // *validi == 1) — semantics identical, the template is only a faster
  // route for lines json.loads would accept.
  {
    static const char kHead[] = "{\"numericalFeatures\": ";
    static const char kCat[] = ", \"categoricalFeatures\": ";
    static const char kTgt[] = ", \"target\": ";
    static const char kOp[] = ", \"operation\": \"training\"}";
    const long kHeadLen = sizeof(kHead) - 1;
    const long kCatLen = sizeof(kCat) - 1;
    const long kTgtLen = sizeof(kTgt) - 1;
    const long kOpLen = sizeof(kOp) - 1;
    if (ll > kHeadLen + kCatLen + kTgtLen + kOpLen &&
        hash_space > 0 && hash_space <= 0xFFFFFFFFL &&
        memcmp(q, kHead, kHeadLen) == 0 && q[kHeadLen] == '[') {
      Cursor t{q + kHeadLen + 1, line_end};
      int tk = 0;
      long tpos = 0;
      bool tany = false;
      if (parse_num_items_coo(t, dense_budget, max_nnz, ii, vv, tk, tpos,
                              tany) == 0 &&
          line_end - t.p > kCatLen &&
          memcmp(t.p, kCat, kCatLen) == 0 && t.p[kCatLen] == '[') {
        t.p += kCatLen + 1;
        int rc = parse_cat_items(t, dense_budget, hash_mod, max_nnz, ii,
                                 vv, tk, tany);
        if (rc == 2) { *validi = 2; return; }  // same verdict either route
        if (rc == 0 && line_end - t.p >= kTgtLen &&
            memcmp(t.p, kTgt, kTgtLen) == 0) {
          t.p += kTgtLen;
          double tv;
          if (parse_number(t, &tv) && line_end - t.p >= kOpLen &&
              memcmp(t.p, kOp, kOpLen) == 0) {
            t.p += kOpLen;
            while (t.p < line_end && is_edge_ws(*t.p)) ++t.p;
            if (t.p == line_end) {
              for (int z = tk; z < max_nnz; ++z) { ii[z] = 0; vv[z] = 0.0f; }
              *yi = to_f32_clamped(tv);
              *opi = 0;
              *validi = tany ? 1 : 0;
              return;
            }
          }
        }
      }
    }
  }

  Cursor c{q + 1, line_end};
  bool ok = true;
  bool have_target = false, have_op = false;
  double target = 0.0;
  int op_val = -1;
  int k = 0;        // COO slots used
  long pos = 0;     // dense positional cursor
  bool num_seen = false, disc_seen = false, cat_seen = false;
  bool any = false;
  bool closed = false;
  bool first = true;

  while (ok && c.p < c.end) {
    skip_ws(c);
    if (c.p < c.end && *c.p == '}') {
      ++c.p;
      closed = true;
      break;
    }
    if (!first) {
      if (c.p >= c.end || *c.p != ',') { ok = false; break; }
      ++c.p;
      skip_ws(c);
      if (c.p < c.end && *c.p == '}') { ok = false; break; }
    }
    first = false;
    if (c.p >= c.end || *c.p != '"') { ok = false; break; }
    const char* ks = c.p + 1;
    if (!skip_string(c)) { ok = false; break; }
    const char* ke = c.p - 1;
    skip_ws(c);
    if (c.p >= c.end || *c.p != ':') { ok = false; break; }
    ++c.p;
    skip_ws(c);
    switch (match_key(ks, ke - ks)) {
      case KEY_METADATA:
        *validi = 2;
        return;
      case KEY_NUMERICAL:
      case KEY_DISCRETE: {
        bool dup = (match_key(ks, ke - ks) == KEY_NUMERICAL)
                       ? num_seen : disc_seen;
        if (dup) { *validi = 2; return; }
        if (match_key(ks, ke - ks) == KEY_NUMERICAL) num_seen = true;
        else disc_seen = true;
        // ordering parity: SparseVectorizer packs numerical, then
        // discrete, then categorical REGARDLESS of JSON key order; any
        // line whose keys arrive out of that order defers to Python so
        // the COO slot order (and the max_nnz truncation set) match
        if (cat_seen ||
            (match_key(ks, ke - ks) == KEY_NUMERICAL && disc_seen &&
             pos > 0)) {
          *validi = 2;
          return;
        }
        if (c.p >= c.end || *c.p != '[') {
          int r = check_value(c);
          if (r == 0) ok = false; else if (r == 2) { *validi = 2; return; }
          break;
        }
        ++c.p;
        if (parse_num_items_coo(c, dense_budget, max_nnz, ii, vv, k, pos,
                                any) != 0)
          ok = false;
        break;
      }
      case KEY_CATEGORICAL: {
        if (cat_seen) { *validi = 2; return; }
        cat_seen = true;
        // hash_space must fit uint32 for the fastmod (and the old 32-bit
        // %); larger spaces defer to the full-precision Python hasher
        if (hash_space <= 0 || hash_space > 0xFFFFFFFFL) {
          *validi = 2;
          return;
        }
        if (c.p >= c.end || *c.p != '[') {
          int r = check_value(c);
          if (r == 0) ok = false; else if (r == 2) { *validi = 2; return; }
          break;
        }
        ++c.p;
        int rc = parse_cat_items(c, dense_budget, hash_mod, max_nnz, ii,
                                 vv, k, any);
        if (rc == 2) { *validi = 2; return; }
        if (rc != 0) ok = false;
        break;
      }
      case KEY_TARGET: {
        Cursor t{c.p, line_end};
        if (parse_number(t, &target)) {
          have_target = true;
          c.p = t.p;
        } else if (c.end - c.p >= 4 && strncmp(c.p, "null", 4) == 0) {
          have_target = false;
          target = 0.0;
          c.p += 4;
        } else {
          *validi = 2;
          return;
        }
        break;
      }
      case KEY_OPERATION: {
        have_op = true;
        op_val = -1;
        if (c.p < c.end && *c.p == '"') {
          const char* vs = c.p + 1;
          if (!skip_string(c)) { ok = false; break; }
          const char* ve = c.p - 1;
          long vl = ve - vs;
          if (memchr(vs, '\\', vl) != nullptr) { *validi = 2; return; }
          if (vl == 11 && strncmp(vs, "forecasting", 11) == 0) op_val = 1;
          else if (vl == 8 && strncmp(vs, "training", 8) == 0) op_val = 0;
        } else {
          int r = check_value(c);
          if (r == 0) ok = false;
          else if (r == 2) { *validi = 2; return; }
        }
        break;
      }
      case KEY_UNKNOWN: {
        int r = check_value(c);
        if (r == 0) ok = false;
        else if (r == 2) { *validi = 2; return; }
        break;
      }
    }
  }
  if (!ok || !closed) return;
  while (c.p < c.end && is_edge_ws(*c.p)) ++c.p;
  if (c.p < c.end) return;
  // zero-fill the unused COO slots (pad idx 0 / val 0 is inert)
  for (int z = k; z < max_nnz; ++z) { ii[z] = 0; vv[z] = 0.0f; }
  if (have_target) *yi = to_f32_clamped(target);
  if (have_op) {
    if (op_val < 0) return;
    *opi = static_cast<unsigned char>(op_val);
  }
  *validi = any ? 1 : 0;
}

// Shared multithreaded line driver: index newline offsets, then run
// ``per_line(i, line, line_end)`` over disjoint line ranges on
// std::threads (each line owns its output row; nothing is shared).
// Returns lines consumed; stores the consumed byte offset.
template <typename F>
int mt_line_driver(const char* buf, long len, int max_records,
                   int n_threads, long* bytes_consumed, F per_line) {
  std::vector<long> starts;
  starts.reserve(4096);
  const char* p = buf;
  const char* bufend = buf + len;
  while (p < bufend && static_cast<int>(starts.size()) < max_records) {
    starts.push_back(p - buf);
    const char* nl = static_cast<const char*>(memchr(p, '\n', bufend - p));
    p = nl ? nl + 1 : bufend;
  }
  const long consumed = p - buf;
  if (bytes_consumed) *bytes_consumed = consumed;
  int n = static_cast<int>(starts.size());
  if (n == 0) return 0;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;

  auto worker = [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      const char* line = buf + starts[i];
      // starts[i+1]-1 lands on the '\n'; the final indexed line ends at
      // the consumed offset (== len unless max_records truncated)
      long line_len =
          ((i + 1 < n) ? starts[i + 1] - 1 : consumed) - starts[i];
      if (line_len < 0) line_len = 0;
      const char* line_end = line + line_len;
      if (line_end > bufend) line_end = bufend;
      if (line_end > line && line_end[-1] == '\n') --line_end;
      per_line(i, line, line_end);
    }
  };
  if (n_threads == 1) {
    worker(0, n);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(n_threads);
    int chunk = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
      int lo = t * chunk;
      int hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      threads.emplace_back(worker, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  return n;
}

}  // namespace

extern "C" {

int omldm_parse_lines(const char* buf, long len, int dim, int max_records,
                      float* x, float* y, unsigned char* op,
                      unsigned char* valid, long* bytes_consumed) {
  const char* p = buf;
  const char* bufend = buf + len;
  int i = 0;
  while (p < bufend && i < max_records) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', bufend - p));
    const char* line_end = nl ? nl : bufend;
    parse_one_line(p, line_end, dim, x + static_cast<long>(i) * dim, y + i,
                   op + i, valid + i);
    ++i;
    p = nl ? nl + 1 : bufend;
  }
  if (bytes_consumed) *bytes_consumed = p - buf;
  return i;
}

// --- fused parse -> holdout -> stage -------------------------------------
//
// The e2e hot loop (SPMDBridge.handle_batch -> _train_rows -> _stage_rows)
// re-touches every row several times in numpy: batcher copy, holdout
// split/concatenate, stage memcpy. This entry fuses the whole per-record
// path (FlinkSpoke.scala:92-107 semantics) into the parse itself: each line
// is parsed DIRECTLY into its stage slot, the 8-of-10 holdout cycle
// (counts 8,9 of each 0-9 cycle) runs in place, and ring eviction swaps the
// evicted row into the very slot the arriving row was parsed into — the
// evicted point re-enters training at the evicting row's stream position,
// exact ArrayHoldout.append_many parity. Rare lines (Python-codec fallback,
// forecasts) return control to the caller so the hot loop stays pure C.
struct OmldmStageCtx {
  float* stage_x;       // [stage_cap, row_stride] training stage
  float* stage_y;       // [stage_cap]
  long long stage_cap;
  long long stage_n;
  float* hold_x;        // [hold_cap, row_stride] holdout ring
  float* hold_y;        // [hold_cap]
  long long hold_cap;
  long long hold_n;
  long long hold_head;  // oldest element
  long long holdout_count;  // position in the 0-9 holdout cycle
  long long row_stride;     // floats per stage/holdout row (>= n_features)
  int n_features;           // dense parse budget (row_stride - hash_dims)
  int test_enabled;
};

namespace {

// Holdout-split one training row already sitting in its stage slot.
// Returns 1 if the row stays staged (slot consumed), 0 if it moved to the
// holdout ring (slot free for reuse).
inline int stage_holdout_slot(OmldmStageCtx* ctx, float* slot, float yv) {
  long long cyc = ctx->holdout_count % 10;
  ctx->holdout_count++;
  if (ctx->test_enabled && cyc >= 8 && ctx->hold_cap > 0) {
    long long stride = ctx->row_stride;
    if (ctx->hold_n < ctx->hold_cap) {
      long long pos = (ctx->hold_head + ctx->hold_n) % ctx->hold_cap;
      memcpy(ctx->hold_x + pos * stride, slot,
             sizeof(float) * static_cast<size_t>(stride));
      ctx->hold_y[pos] = yv;
      ctx->hold_n++;
      return 0;
    }
    // ring full: swap the oldest row into this slot (it re-enters training
    // here) and store the arriving row in its place
    long long pos = ctx->hold_head;
    float* ring = ctx->hold_x + pos * stride;
    for (long long i = 0; i < stride; ++i) {
      float t = ring[i];
      ring[i] = slot[i];
      slot[i] = t;
    }
    float ty = ctx->hold_y[pos];
    ctx->hold_y[pos] = yv;
    yv = ty;
    ctx->hold_head = (ctx->hold_head + 1) % ctx->hold_cap;
  }
  ctx->stage_y[ctx->stage_n] = yv;
  ctx->stage_n++;
  return 1;
}

}  // namespace

// Parse a block of whole JSON lines straight into the staging buffers.
// Returns:
//   0  buffer fully consumed
//   1  stage full (caller launches the device step, resets stage_n, resumes)
//   2  fallback line (Python codec decides; [*special_off, +*special_len))
//   3  forecast row (features in fore_x[0..row_stride), target in *fore_y)
// *bytes_consumed is the resume offset relative to buf in all cases (for
// 2/3 it points past the special line).
int omldm_parse_stage(const char* buf, long long len, OmldmStageCtx* ctx,
                      long long* bytes_consumed, long long* special_off,
                      long long* special_len, float* fore_x, float* fore_y) {
  const char* p = buf;
  const char* bufend = buf + len;
  const long long stride = ctx->row_stride;
  const int nfeat = ctx->n_features;
  while (p < bufend) {
    if (ctx->stage_n >= ctx->stage_cap) {
      *bytes_consumed = p - buf;
      return 1;
    }
    const char* nl = static_cast<const char*>(memchr(p, '\n', bufend - p));
    const char* line_end = nl ? nl : bufend;
    const char* next = nl ? nl + 1 : bufend;
    float* slot = ctx->stage_x + ctx->stage_n * stride;
    float yv;
    unsigned char opv, validv;
    parse_one_line(p, line_end, nfeat, slot, &yv, &opv, &validv);
    if (validv == 1) {
      if (stride > nfeat)  // zero the hashed-categorical tail (slot reuse)
        memset(slot + nfeat, 0,
               sizeof(float) * static_cast<size_t>(stride - nfeat));
      if (opv == 1) {
        memcpy(fore_x, slot, sizeof(float) * static_cast<size_t>(stride));
        *fore_y = yv;
        *bytes_consumed = next - buf;
        return 3;
      }
      stage_holdout_slot(ctx, slot, yv);
    } else if (validv == 2) {
      *special_off = p - buf;
      *special_len = line_end - p;
      *bytes_consumed = next - buf;
      return 2;
    }
    p = next;
  }
  *bytes_consumed = len;
  return 0;
}

// --- fused SPARSE parse -> holdout -> stage ------------------------------
//
// The padded-COO twin of omldm_parse_stage: the sparse e2e hot loop
// (SparseSPMDBridge.ingest_file -> _consume_coo_block -> _train_sparse_rows
// -> _stage_coo) re-touched every row several times in numpy — per-block
// output allocation + concatenate in the parser driver, the vectorized
// holdout split (mask/argsort/concatenate), and the stage memcpy. This
// entry parses each line DIRECTLY into its COO stage slot, runs the 8-of-10
// holdout cycle in place against the sparse holdout ring (idx/val/y
// triple), and swaps evicted rows into the arriving row's slot — exact
// SparseHoldout.append_many + _holdout_then_stage parity, pinned by
// tests/test_sparse_spmd_bridge.py. Specials (Python-codec fallbacks AND
// forecasts — both re-enter through DataInstance.from_json -> handle_data
// exactly like the block route's special path) return control to the
// caller; the hot loop stays pure C.
struct OmldmSparseStageCtx {
  int32_t* stage_i;     // [stage_cap, max_nnz] COO index stage
  float* stage_v;       // [stage_cap, max_nnz] COO value stage
  float* stage_y;       // [stage_cap]
  long long stage_cap;
  long long stage_n;
  int32_t* hold_i;      // [hold_cap, max_nnz] holdout ring
  float* hold_v;        // [hold_cap, max_nnz]
  float* hold_y;        // [hold_cap]
  long long hold_cap;
  long long hold_n;
  long long hold_head;      // oldest element
  long long holdout_count;  // position in the 0-9 holdout cycle
  int max_nnz;
  int dense_budget;         // positional slots before the hashed region
  long long hash_space;
  int test_enabled;
};

namespace {

// Holdout-split one COO training row already sitting in its stage slot
// (the sparse form of stage_holdout_slot; same return convention).
inline int sparse_stage_holdout_slot(OmldmSparseStageCtx* ctx, int32_t* si,
                                     float* sv, float yv) {
  long long cyc = ctx->holdout_count % 10;
  ctx->holdout_count++;
  if (ctx->test_enabled && cyc >= 8 && ctx->hold_cap > 0) {
    const size_t k = static_cast<size_t>(ctx->max_nnz);
    if (ctx->hold_n < ctx->hold_cap) {
      long long pos = (ctx->hold_head + ctx->hold_n) % ctx->hold_cap;
      memcpy(ctx->hold_i + pos * static_cast<long long>(k), si,
             sizeof(int32_t) * k);
      memcpy(ctx->hold_v + pos * static_cast<long long>(k), sv,
             sizeof(float) * k);
      ctx->hold_y[pos] = yv;
      ctx->hold_n++;
      return 0;
    }
    // ring full: swap the oldest row into this slot (it re-enters training
    // at the evicting row's stream position) and store the arriving row
    long long pos = ctx->hold_head;
    int32_t* ri = ctx->hold_i + pos * static_cast<long long>(k);
    float* rv = ctx->hold_v + pos * static_cast<long long>(k);
    for (size_t i = 0; i < k; ++i) {
      int32_t ti = ri[i];
      ri[i] = si[i];
      si[i] = ti;
      float tv = rv[i];
      rv[i] = sv[i];
      sv[i] = tv;
    }
    float ty = ctx->hold_y[pos];
    ctx->hold_y[pos] = yv;
    yv = ty;
    ctx->hold_head = (ctx->hold_head + 1) % ctx->hold_cap;
  }
  ctx->stage_y[ctx->stage_n] = yv;
  ctx->stage_n++;
  return 1;
}

}  // namespace

// Parse a block of whole JSON lines straight into the COO staging buffers.
// Returns:
//   0  buffer fully consumed
//   1  stage full (caller launches the staged step, resets stage_n, resumes)
//   2  special line (codec fallback OR forecast — the caller re-parses
//      [*special_off, +*special_len) with the Python codec, whose
//      handle_data path serves forecasts and odd schemas identically to
//      the block route)
// *bytes_consumed is the resume offset relative to buf in all cases (for
// 2 it points past the special line).
int omldm_parse_stage_sparse(const char* buf, long long len,
                             OmldmSparseStageCtx* ctx,
                             long long* bytes_consumed,
                             long long* special_off,
                             long long* special_len) {
  const char* p = buf;
  const char* bufend = buf + len;
  const int k = ctx->max_nnz;
  const bool hash_fits =
      ctx->hash_space > 0 && ctx->hash_space <= 0xFFFFFFFFL;
  const FastMod hash_mod(
      hash_fits ? static_cast<uint32_t>(ctx->hash_space) : 1u);
  while (p < bufend) {
    if (ctx->stage_n >= ctx->stage_cap) {
      *bytes_consumed = p - buf;
      return 1;
    }
    const char* nl = static_cast<const char*>(memchr(p, '\n', bufend - p));
    const char* line_end = nl ? nl : bufend;
    const char* next = nl ? nl + 1 : bufend;
    int32_t* si = ctx->stage_i + ctx->stage_n * static_cast<long long>(k);
    float* sv = ctx->stage_v + ctx->stage_n * static_cast<long long>(k);
    float yv;
    unsigned char opv, validv;
    parse_one_line_sparse(p, line_end, ctx->dense_budget, ctx->hash_space,
                          hash_mod, k, si, sv, &yv, &opv, &validv);
    if (validv == 1 && opv == 0) {
      sparse_stage_holdout_slot(ctx, si, sv, yv);
    } else if (validv == 2 || (validv == 1 && opv == 1)) {
      *special_off = p - buf;
      *special_len = line_end - p;
      *bytes_consumed = next - buf;
      return 2;
    }
    p = next;
  }
  *bytes_consumed = len;
  return 0;
}

// Stage a run of ALREADY-PARSED COO training rows: the staging tail of the
// multithreaded block route (omldm_parse_lines_sparse_mt parses on all
// cores, then this serial pass runs the 8-of-10 holdout cycle + ring swap
// + stage memcpy in C — the work the numpy _holdout_then_stage/_stage_coo
// pair used to do with mask/argsort/concatenate per block). Pauses at
// stage-full so the caller can launch the staged step; returns rows
// consumed from [0, n). Bit-identical to the fused line loop above and to
// the numpy route (all three share the per-record holdout semantics).
long long omldm_stage_coo_rows(OmldmSparseStageCtx* ctx, const int32_t* idx,
                               const float* val, const float* y,
                               long long n) {
  const long long k = ctx->max_nnz;
  long long i = 0;
  while (i < n) {
    if (ctx->stage_n >= ctx->stage_cap) break;
    int32_t* si = ctx->stage_i + ctx->stage_n * k;
    float* sv = ctx->stage_v + ctx->stage_n * k;
    memcpy(si, idx + i * k, sizeof(int32_t) * static_cast<size_t>(k));
    memcpy(sv, val + i * k, sizeof(float) * static_cast<size_t>(k));
    sparse_stage_holdout_slot(ctx, si, sv, y[i]);
    ++i;
  }
  return i;
}

// Sparse bulk entry: JSON lines -> padded-COO (idx[max_nnz], val[max_nnz])
// rows + targets/ops/valid, mirroring omldm_parse_lines' contract.
int omldm_parse_lines_sparse(const char* buf, long len, int dense_budget,
                             long hash_space, int max_nnz, int max_records,
                             int32_t* idx, float* val, float* y,
                             unsigned char* op, unsigned char* valid,
                             long* bytes_consumed) {
  const char* p = buf;
  const char* bufend = buf + len;
  int i = 0;
  const bool hash_fits = hash_space > 0 && hash_space <= 0xFFFFFFFFL;
  const FastMod hash_mod(
      hash_fits ? static_cast<uint32_t>(hash_space) : 1u);
  while (p < bufend && i < max_records) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', bufend - p));
    const char* line_end = nl ? nl : bufend;
    parse_one_line_sparse(p, line_end, dense_budget, hash_space, hash_mod,
                          max_nnz,
                          idx + static_cast<long>(i) * max_nnz,
                          val + static_cast<long>(i) * max_nnz, y + i,
                          op + i, valid + i);
    ++i;
    p = nl ? nl + 1 : bufend;
  }
  if (bytes_consumed) *bytes_consumed = p - buf;
  return i;
}

int omldm_parse_lines_mt(const char* buf, long len, int dim, int max_records,
                         float* x, float* y, unsigned char* op,
                         unsigned char* valid, int n_threads,
                         long* bytes_consumed) {
  return mt_line_driver(
      buf, len, max_records, n_threads, bytes_consumed,
      [&](int i, const char* line, const char* line_end) {
        parse_one_line(line, line_end, dim, x + static_cast<long>(i) * dim,
                       y + i, op + i, valid + i);
      });
}

int omldm_parse_lines_sparse_mt(const char* buf, long len, int dense_budget,
                                long hash_space, int max_nnz,
                                int max_records, int32_t* idx, float* val,
                                float* y, unsigned char* op,
                                unsigned char* valid, int n_threads,
                                long* bytes_consumed) {
  const bool hash_fits = hash_space > 0 && hash_space <= 0xFFFFFFFFL;
  const FastMod hash_mod(
      hash_fits ? static_cast<uint32_t>(hash_space) : 1u);
  return mt_line_driver(
      buf, len, max_records, n_threads, bytes_consumed,
      [&](int i, const char* line, const char* line_end) {
        parse_one_line_sparse(line, line_end, dense_budget, hash_space,
                              hash_mod, max_nnz,
                              idx + static_cast<long>(i) * max_nnz,
                              val + static_cast<long>(i) * max_nnz, y + i,
                              op + i, valid + i);
      });
}

}  // extern "C"
