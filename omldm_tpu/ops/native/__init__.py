"""Native (C++) components, built on demand with the system toolchain.

The reference's native layer is the ND4J/OpenBLAS tensor backend reached over
JNI (SURVEY.md section 2.3); on TPU the tensor backend is XLA itself, so the
native budget goes where the host is the bottleneck: stream ingest. The
fast parser compiles ``fastparse.cpp`` with g++ into a shared object loaded
via ctypes (no pybind11 in this image) and falls back to the pure-Python
parser when a toolchain is unavailable.
"""

from omldm_tpu.ops.native.loader import (
    FastParser,
    FusedStage,
    SparseFastParser,
    SparseFusedStage,
    fast_parser_available,
)

__all__ = [
    "FastParser",
    "FusedStage",
    "SparseFastParser",
    "SparseFusedStage",
    "fast_parser_available",
]
