"""ctypes loader + on-demand build of the native record parser."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fastparse.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libfastparse.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if not os.path.exists(_LIB_PATH) or (
        os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
    ):
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-pthread",
            "-o", _LIB_PATH, _SRC,
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True, timeout=120
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            _build_failed = True
            return None
    lib = ctypes.CDLL(_LIB_PATH)
    base_argtypes = [
        ctypes.c_char_p,
        ctypes.c_long,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_ubyte),
    ]
    lib.omldm_parse_lines.restype = ctypes.c_int
    lib.omldm_parse_lines.argtypes = base_argtypes
    lib.omldm_parse_lines_mt.restype = ctypes.c_int
    lib.omldm_parse_lines_mt.argtypes = base_argtypes + [ctypes.c_int]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                _lib = _build()
    return _lib


def fast_parser_available() -> bool:
    return _get_lib() is not None


class FastParser:
    """Bulk JSON-lines -> packed (x, y, op, valid) arrays.

    ``valid`` semantics (see fastparse.cpp): 1 = parsed, 0 = dropped,
    2 = needs the Python fallback (categorical features / metadata);
    callers reparse flagged lines with ``DataInstance.from_json``.

    ``n_threads`` > 1 uses the multithreaded C entry (disjoint line ranges
    per std::thread; ctypes releases the GIL for the call's duration, so a
    prefetch thread parsing blocks overlaps the device feed)."""

    def __init__(self, dim: int, n_threads: int = 0):
        self.dim = dim
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 8)
        self.n_threads = n_threads
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native fast parser unavailable (g++ build failed)")
        self._lib = lib

    def parse(
        self, data: bytes
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        n_lines = data.count(b"\n") + (0 if data.endswith(b"\n") or not data else 1)
        n_lines = max(n_lines, 1)
        x = np.zeros((n_lines, self.dim), np.float32)
        y = np.zeros((n_lines,), np.float32)
        op = np.zeros((n_lines,), np.uint8)
        valid = np.zeros((n_lines,), np.uint8)
        args = (
            data,
            len(data),
            self.dim,
            n_lines,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            op.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            valid.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        )
        if self.n_threads > 1:
            consumed = self._lib.omldm_parse_lines_mt(*args, self.n_threads)
        else:
            consumed = self._lib.omldm_parse_lines(*args)
        return x[:consumed], y[:consumed], op[:consumed], valid[:consumed]
