"""ctypes loader + on-demand build of the native record parser."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fastparse.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")


def _host_tag() -> str:
    """ISA identity for the build cache: -march=native output is only
    valid on CPUs with the same feature set, and the cache can travel
    inside the package tree (containers, shared volumes) — a stale lib
    would SIGILL with no catchable error."""
    import hashlib
    import platform

    ident = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    ident += hashlib.sha1(line.encode()).hexdigest()[:12]
                    break
    except OSError:
        pass
    return ident


_LIB_PATH = os.path.join(_BUILD_DIR, f"libfastparse_{_host_tag()}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if not os.path.exists(_LIB_PATH) or (
        os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
    ):
        base = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-o", _LIB_PATH, _SRC]
        # -march=native squeezes a few percent out of the SWAR paths; the
        # plain build is the fallback for toolchains/CPUs that reject it
        ok = False
        for cmd in (base[:1] + ["-march=native"] + base[1:], base):
            try:
                subprocess.run(
                    cmd, check=True, capture_output=True, text=True, timeout=120
                )
                ok = True
                break
            except (subprocess.SubprocessError, FileNotFoundError, OSError):
                continue
        if not ok:
            _build_failed = True
            return None
    lib = ctypes.CDLL(_LIB_PATH)
    base_argtypes = [
        ctypes.c_void_p,
        ctypes.c_long,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_ubyte),
    ]
    consumed_p = ctypes.POINTER(ctypes.c_long)
    lib.omldm_parse_lines.restype = ctypes.c_int
    lib.omldm_parse_lines.argtypes = base_argtypes + [consumed_p]
    lib.omldm_parse_lines_mt.restype = ctypes.c_int
    lib.omldm_parse_lines_mt.argtypes = base_argtypes + [ctypes.c_int, consumed_p]
    ll_p = ctypes.POINTER(ctypes.c_longlong)
    f_p = ctypes.POINTER(ctypes.c_float)
    i32_p = ctypes.POINTER(ctypes.c_int32)
    sparse_argtypes = [
        ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_long,
        ctypes.c_int, ctypes.c_int, i32_p,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_ubyte),
    ]
    lib.omldm_parse_lines_sparse.restype = ctypes.c_int
    lib.omldm_parse_lines_sparse.argtypes = sparse_argtypes + [consumed_p]
    lib.omldm_parse_lines_sparse_mt.restype = ctypes.c_int
    lib.omldm_parse_lines_sparse_mt.argtypes = sparse_argtypes + [
        ctypes.c_int, consumed_p,
    ]
    lib.omldm_parse_stage.restype = ctypes.c_int
    lib.omldm_parse_stage.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.POINTER(StageCtx),
        ll_p, ll_p, ll_p, f_p, f_p,
    ]
    lib.omldm_parse_stage_sparse.restype = ctypes.c_int
    lib.omldm_parse_stage_sparse.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong,
        ctypes.POINTER(SparseStageCtx), ll_p, ll_p, ll_p,
    ]
    lib.omldm_stage_coo_rows.restype = ctypes.c_longlong
    lib.omldm_stage_coo_rows.argtypes = [
        ctypes.POINTER(SparseStageCtx), i32_p,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_longlong,
    ]
    return lib


class StageCtx(ctypes.Structure):
    """Mirror of OmldmStageCtx (fastparse.cpp): the fused
    parse->holdout->stage loop's view of the caller's staging buffers."""

    _fields_ = [
        ("stage_x", ctypes.POINTER(ctypes.c_float)),
        ("stage_y", ctypes.POINTER(ctypes.c_float)),
        ("stage_cap", ctypes.c_longlong),
        ("stage_n", ctypes.c_longlong),
        ("hold_x", ctypes.POINTER(ctypes.c_float)),
        ("hold_y", ctypes.POINTER(ctypes.c_float)),
        ("hold_cap", ctypes.c_longlong),
        ("hold_n", ctypes.c_longlong),
        ("hold_head", ctypes.c_longlong),
        ("holdout_count", ctypes.c_longlong),
        ("row_stride", ctypes.c_longlong),
        ("n_features", ctypes.c_int),
        ("test_enabled", ctypes.c_int),
    ]


class SparseStageCtx(ctypes.Structure):
    """Mirror of OmldmSparseStageCtx (fastparse.cpp): the fused sparse
    parse->holdout->stage loop's view of the caller's padded-COO staging
    buffers and holdout ring."""

    _fields_ = [
        ("stage_i", ctypes.POINTER(ctypes.c_int32)),
        ("stage_v", ctypes.POINTER(ctypes.c_float)),
        ("stage_y", ctypes.POINTER(ctypes.c_float)),
        ("stage_cap", ctypes.c_longlong),
        ("stage_n", ctypes.c_longlong),
        ("hold_i", ctypes.POINTER(ctypes.c_int32)),
        ("hold_v", ctypes.POINTER(ctypes.c_float)),
        ("hold_y", ctypes.POINTER(ctypes.c_float)),
        ("hold_cap", ctypes.c_longlong),
        ("hold_n", ctypes.c_longlong),
        ("hold_head", ctypes.c_longlong),
        ("holdout_count", ctypes.c_longlong),
        ("max_nnz", ctypes.c_int),
        ("dense_budget", ctypes.c_int),
        ("hash_space", ctypes.c_longlong),
        ("test_enabled", ctypes.c_int),
    ]


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                _lib = _build()
    return _lib


def fast_parser_available() -> bool:
    return _get_lib() is not None


class SparseFastParser:
    """Bulk JSON-lines -> padded-COO ((idx, val)[., K], y, op, valid)
    arrays — the sparse twin of :class:`FastParser`. ``valid`` semantics
    match: 1 parsed, 0 dropped, 2 Python-codec fallback (escaped category
    strings, out-of-order keys, metadata, odd scalars). Dense values keep
    positional slots; categoricals hash with zlib-CRC32("{i}={cat}") into
    ``[dense_budget, dense_budget + hash_space)`` with the signed rule —
    bit-identical to SparseVectorizer.vectorize (fuzz-pinned)."""

    def __init__(self, dense_budget: int, hash_space: int, max_nnz: int,
                 n_threads: int = 0, reuse_buffers: bool = False):
        self.dense_budget = dense_budget
        self.hash_space = hash_space
        self.max_nnz = max_nnz
        # <= 0 = auto (FastParser's rule: min(cores, 8)); > 1 parses
        # disjoint line ranges on C threads (each line owns its output
        # row; the CRC prefix cache is thread_local) — the sparse e2e
        # path is parse-bound, so multi-core hosts scale it with the same
        # _mt scheme as the dense parser
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 8)
        self.n_threads = int(n_threads)
        # reuse_buffers: return VIEWS into a persistent scratch instead of
        # fresh np.empty outputs per call. Fresh multi-MB allocations come
        # back from the allocator as unfaulted mmap pages, so the C parser
        # pays a page fault every 4 KB it writes plus munmap TLB
        # shootdowns on free — measured ~15% of the whole sparse parse at
        # Criteo chunk sizes. Only callers that finish with the returned
        # arrays before the next parse call may opt in (the bridge ingest
        # routes do: staging memcpys/copies complete per chunk).
        self.reuse_buffers = bool(reuse_buffers)
        self._scratch = None
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native fast parser unavailable (g++ build failed)")
        self._lib = lib

    def _outputs(self, n_cap: int):
        k = self.max_nnz
        if not self.reuse_buffers:
            return (
                np.empty((n_cap, k), np.int32),
                np.empty((n_cap, k), np.float32),
                np.empty((n_cap,), np.float32),
                np.empty((n_cap,), np.uint8),
                np.empty((n_cap,), np.uint8),
            )
        if self._scratch is None or self._scratch[0].shape[0] < n_cap:
            self._scratch = (
                np.empty((n_cap, k), np.int32),
                np.empty((n_cap, k), np.float32),
                np.empty((n_cap,), np.float32),
                np.empty((n_cap,), np.uint8),
                np.empty((n_cap,), np.uint8),
            )
        return self._scratch

    def _parse_at(self, addr: int, length: int, n_cap: int):
        idx, val, y, op, valid = self._outputs(n_cap)
        n_cap = idx.shape[0]  # a grown scratch can take more rows
        done = ctypes.c_long(0)
        common = (
            ctypes.c_void_p(addr), length, self.dense_budget,
            self.hash_space, self.max_nnz, n_cap,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            op.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            valid.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        )
        if self.n_threads > 1:
            n = self._lib.omldm_parse_lines_sparse_mt(
                *common, self.n_threads, ctypes.byref(done)
            )
        else:
            n = self._lib.omldm_parse_lines_sparse(
                *common, ctypes.byref(done)
            )
        return idx[:n], val[:n], y[:n], op[:n], valid[:n], done.value

    def _empty(self):
        k = self.max_nnz
        return (
            np.empty((0, k), np.int32), np.empty((0, k), np.float32),
            np.empty(0, np.float32), np.empty(0, np.uint8),
            np.empty(0, np.uint8),
        )

    def _parse_region(self, addr: int, length: int, nl_sample: int):
        # size the row estimate from a sampled average line length (sparse
        # records run hundreds of bytes; a fixed 48-byte guess would
        # over-allocate the [n, K] outputs several-fold)
        window = min(length, 1 << 16)
        avg = max(window // max(nl_sample, 1), 8)
        est = length // avg + length // (8 * avg) + 16
        parts = []
        offset = 0
        while offset < length:
            if parts and self.reuse_buffers:
                # a second pass reuses the scratch the previous part views:
                # materialize it first (rare — only on an underestimate)
                parts[-1] = tuple(np.array(a, copy=True) for a in parts[-1])
            out = self._parse_at(addr + offset, length - offset, est)
            parts.append(out[:5])
            offset += out[5]
            est = (length - offset) // avg + 16
        if len(parts) == 1:
            return parts[0]
        return tuple(
            np.concatenate([p[i] for p in parts]) for i in range(5)
        )

    def parse(self, data: bytes):
        if not data:
            return self._empty()
        addr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
        length = len(data)
        return self._parse_region(
            addr, length, data[: min(length, 1 << 16)].count(b"\n")
        )

    def parse_range(self, buf: bytearray, start: int, stop: int):
        """Zero-copy parse of ``buf[start:stop]`` (a writable buffer the
        caller reuses across reads — the sparse block-ingest path; bytes
        are only materialized when a line needs the Python fallback)."""
        if stop <= start:
            return self._empty()
        base = ctypes.addressof(
            (ctypes.c_char * len(buf)).from_buffer(buf)
        )
        window_stop = min(stop, start + (1 << 16))
        return self._parse_region(
            base + start, stop - start, buf.count(b"\n", start, window_stop)
        )


class FusedStage:
    """Driver for the fused C parse->holdout->stage loop (omldm_parse_stage).

    Owns the ctypes ``StageCtx`` describing the caller's staging/holdout
    numpy buffers; the caller syncs the mutable cursors (stage_n, holdout
    ring state, holdout cycle counter) in before each C call and out after,
    so Python-side code (device launches, fallback rows) and the C loop can
    interleave on the same state."""

    RC_DONE = 0       # buffer fully consumed
    RC_STAGE_FULL = 1  # caller launches the staged step and resumes
    RC_FALLBACK = 2   # line needs the Python codec
    RC_FORECAST = 3   # forecast row parsed into fore_x / fore_y

    def __init__(
        self,
        stage_x: np.ndarray,
        stage_y: np.ndarray,
        hold_x: np.ndarray,
        hold_y: np.ndarray,
        n_features: int,
        test_enabled: bool,
    ):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native fast parser unavailable (g++ build failed)")
        self._lib = lib
        for a in (stage_x, stage_y, hold_x, hold_y):
            if a.dtype != np.float32 or not a.flags.c_contiguous:
                raise ValueError("fused stage buffers must be C-contiguous float32")
        if stage_x.shape[1] != hold_x.shape[1]:
            raise ValueError("stage/holdout row widths differ")
        # keep the arrays alive for the ctx's pointer lifetime
        self._arrays = (stage_x, stage_y, hold_x, hold_y)
        f_p = ctypes.POINTER(ctypes.c_float)
        self.ctx = StageCtx(
            stage_x=stage_x.ctypes.data_as(f_p),
            stage_y=stage_y.ctypes.data_as(f_p),
            stage_cap=stage_x.shape[0],
            stage_n=0,
            hold_x=hold_x.ctypes.data_as(f_p),
            hold_y=hold_y.ctypes.data_as(f_p),
            hold_cap=hold_x.shape[0],
            hold_n=0,
            hold_head=0,
            holdout_count=0,
            row_stride=stage_x.shape[1],
            n_features=n_features,
            test_enabled=1 if test_enabled else 0,
        )
        self._fore_x = np.zeros((stage_x.shape[1],), np.float32)
        self._fore_y = ctypes.c_float(0.0)

    def parse_stage(self, buf: bytearray, start: int, stop: int):
        """One C call over ``buf[start:stop]`` (whole JSON lines only).
        Returns (rc, consumed, special_off, special_len); offsets are
        relative to ``start``."""
        base = ctypes.addressof((ctypes.c_char * len(buf)).from_buffer(buf))
        consumed = ctypes.c_longlong(0)
        soff = ctypes.c_longlong(0)
        slen = ctypes.c_longlong(0)
        rc = self._lib.omldm_parse_stage(
            base + start,
            stop - start,
            ctypes.byref(self.ctx),
            ctypes.byref(consumed),
            ctypes.byref(soff),
            ctypes.byref(slen),
            self._fore_x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(self._fore_y),
        )
        return rc, consumed.value, soff.value, slen.value

    def forecast_row(self):
        return self._fore_x, float(self._fore_y.value)


class SparseFusedStage:
    """Driver for the fused sparse C parse->holdout->stage loop
    (omldm_parse_stage_sparse): the padded-COO twin of :class:`FusedStage`.

    Owns the ctypes ``SparseStageCtx`` describing the caller's COO staging
    buffers and sparse holdout ring; the caller syncs the mutable cursors
    (stage_n, holdout ring state, holdout cycle counter) in before each C
    call and out after, exactly like the dense driver. Specials (Python
    fallbacks AND forecasts) surface as one RC_SPECIAL code — both re-enter
    through the Python codec's handle_data path, matching the block route's
    special handling byte for byte."""

    RC_DONE = 0        # buffer fully consumed
    RC_STAGE_FULL = 1  # caller launches the staged step and resumes
    RC_SPECIAL = 2     # line re-enters via DataInstance.from_json

    def __init__(
        self,
        stage_i: np.ndarray,
        stage_v: np.ndarray,
        stage_y: np.ndarray,
        hold_i: np.ndarray,
        hold_v: np.ndarray,
        hold_y: np.ndarray,
        dense_budget: int,
        hash_space: int,
        test_enabled: bool,
    ):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native fast parser unavailable (g++ build failed)")
        self._lib = lib
        for a, dt in (
            (stage_i, np.int32), (stage_v, np.float32), (stage_y, np.float32),
            (hold_i, np.int32), (hold_v, np.float32), (hold_y, np.float32),
        ):
            if a.dtype != dt or not a.flags.c_contiguous:
                raise ValueError(
                    "fused sparse stage buffers must be C-contiguous "
                    "int32 idx / float32 val,y"
                )
        if stage_i.shape[1] != hold_i.shape[1]:
            raise ValueError("stage/holdout max_nnz differ")
        # keep the arrays alive for the ctx's pointer lifetime
        self._arrays = (stage_i, stage_v, stage_y, hold_i, hold_v, hold_y)
        f_p = ctypes.POINTER(ctypes.c_float)
        i_p = ctypes.POINTER(ctypes.c_int32)
        self.ctx = SparseStageCtx(
            stage_i=stage_i.ctypes.data_as(i_p),
            stage_v=stage_v.ctypes.data_as(f_p),
            stage_y=stage_y.ctypes.data_as(f_p),
            stage_cap=stage_i.shape[0],
            stage_n=0,
            hold_i=hold_i.ctypes.data_as(i_p),
            hold_v=hold_v.ctypes.data_as(f_p),
            hold_y=hold_y.ctypes.data_as(f_p),
            hold_cap=hold_i.shape[0],
            hold_n=0,
            hold_head=0,
            holdout_count=0,
            max_nnz=stage_i.shape[1],
            dense_budget=dense_budget,
            hash_space=hash_space,
            test_enabled=1 if test_enabled else 0,
        )

    def parse_stage(self, buf: bytearray, start: int, stop: int):
        """One C call over ``buf[start:stop]`` (whole JSON lines only).
        Returns (rc, consumed, special_off, special_len); offsets are
        relative to ``start``."""
        base = ctypes.addressof((ctypes.c_char * len(buf)).from_buffer(buf))
        consumed = ctypes.c_longlong(0)
        soff = ctypes.c_longlong(0)
        slen = ctypes.c_longlong(0)
        rc = self._lib.omldm_parse_stage_sparse(
            base + start,
            stop - start,
            ctypes.byref(self.ctx),
            ctypes.byref(consumed),
            ctypes.byref(soff),
            ctypes.byref(slen),
        )
        return rc, consumed.value, soff.value, slen.value

    def stage_rows(
        self, idx: np.ndarray, val: np.ndarray, y: np.ndarray, start: int
    ) -> int:
        """Holdout + stage already-parsed COO rows ``[start, n)`` through
        the C stager (omldm_stage_coo_rows — the MT block route's staging
        tail). Pauses at stage-full; returns rows consumed."""
        n = idx.shape[0] - start
        if n <= 0:
            return 0
        iv, vv, yv = idx[start:], val[start:], y[start:]
        return int(
            self._lib.omldm_stage_coo_rows(
                ctypes.byref(self.ctx),
                iv.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                vv.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                yv.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                n,
            )
        )


class FastParser:
    """Bulk JSON-lines -> packed (x, y, op, valid) arrays.

    ``valid`` semantics (see fastparse.cpp): 1 = parsed, 0 = dropped,
    2 = needs the Python fallback (categorical features / metadata);
    callers reparse flagged lines with ``DataInstance.from_json``.

    ``n_threads`` > 1 uses the multithreaded C entry (disjoint line ranges
    per std::thread; ctypes releases the GIL for the call's duration, so a
    prefetch thread parsing blocks overlaps the device feed)."""

    def __init__(self, dim: int, n_threads: int = 0):
        self.dim = dim
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 8)
        self.n_threads = n_threads
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native fast parser unavailable (g++ build failed)")
        self._lib = lib

    def _parse_at(self, addr: int, length: int, n_cap: int):
        """One C call over ``length`` bytes at ``addr``, arrays sized for
        n_cap lines. Returns (x, y, op, valid) sliced to the consumed rows
        + the bytes consumed."""
        # np.empty: y/op/valid are unconditionally stored per consumed
        # line; x rows are only defined where valid == 1 (callers mask or
        # reparse the rest), and the caller slices to the consumed count
        x = np.empty((n_cap, self.dim), np.float32)
        y = np.empty((n_cap,), np.float32)
        op = np.empty((n_cap,), np.uint8)
        valid = np.empty((n_cap,), np.uint8)
        done = ctypes.c_long(0)
        args = (
            ctypes.c_void_p(addr),
            length,
            self.dim,
            n_cap,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            op.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            valid.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        )
        if self.n_threads > 1:
            n = self._lib.omldm_parse_lines_mt(
                *args, self.n_threads, ctypes.byref(done)
            )
        else:
            n = self._lib.omldm_parse_lines(*args, ctypes.byref(done))
        return x[:n], y[:n], op[:n], valid[:n], done.value

    def _parse_region(self, addr: int, length: int):
        # Size the output by an average-line-length estimate instead of a
        # newline-counting pre-pass (which cost ~20% of the whole parse);
        # the C parser reports the bytes it consumed, so an underestimate
        # just means another call over the remainder.
        est = length // 48 + 16
        x, y, op, valid, done = self._parse_at(addr, length, est)
        if done >= length:
            return x, y, op, valid
        parts = [(x, y, op, valid)]
        offset = done
        while offset < length:
            est = (length - offset) // 16 + 16
            x, y, op, valid, done = self._parse_at(
                addr + offset, length - offset, est
            )
            parts.append((x, y, op, valid))
            offset += done
        return tuple(
            np.concatenate([p[i] for p in parts]) for i in range(4)
        )

    def parse(
        self, data: bytes
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        if not data:
            return self._empty()
        addr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
        return self._parse_region(addr, len(data))

    def parse_range(self, buf: bytearray, start: int, stop: int):
        """Zero-copy parse of ``buf[start:stop]`` (a writable buffer the
        caller reuses across reads — the readinto ingest path)."""
        if stop <= start:
            return self._empty()
        base = ctypes.addressof(
            (ctypes.c_char * len(buf)).from_buffer(buf)
        )
        return self._parse_region(base + start, stop - start)

    def _empty(self):
        return (
            np.empty((0, self.dim), np.float32),
            np.empty(0, np.float32),
            np.empty(0, np.uint8),
            np.empty(0, np.uint8),
        )
