"""Calibration harness for the sparse scatter dispatch.

`sparse_scatter_add_auto` (ops/sparse.py) has three formulations of the
same w[idx] += coef*val update with very different cost models:

- ``scatter``: XLA's native scatter-add — serializes per update row on TPU
  (~66M updates/s measured, benchmarks/sparse_scatter_experiment.py) but is
  the natural form everywhere else;
- ``mxu``: the kron-factored one-hot matmul (ops/sparse.py:52) — trades
  ~2*2*D FLOPs per update for the serialization, wins only where the chip's
  matmul rate beats the scatter element rate times D;
- ``segsum``: sort + segmented pre-combine — collapses duplicate hashed
  indices before the scatter, wins when the duplicate factor is high enough
  that the (vectorized) sort costs less than the serialized duplicate adds.

Round 5 shipped the mxu dispatch on a GUESSED ``D >= 2^16`` threshold with
no measured crossover (VERDICT.md weak #3). This module replaces the guess:
it measures all three kernels over a (D, batch, nnz) grid with a
hashed-categorical duplicate profile (each COO slot draws from a ~1k-value
vocabulary, the Criteo/Avazu shape the sparse path exists for), persists
the per-backend crossover table next to this file
(``sparse_dispatch.json``), and `sparse_scatter_add_auto` dispatches from
the table at trace time (nearest grid point in log2 space). Re-run on new
hardware:

    python -m omldm_tpu.ops.sparse_calibrate            # full grid
    python -m omldm_tpu.ops.sparse_calibrate --smoke    # CI-sized grid

Writes merge per backend, so a TPU calibration does not clobber the CPU
section. ``OMLDM_SPARSE_SCATTER_TABLE`` points the lookup (and the writer)
at an alternate table path; ``OMLDM_SPARSE_SCATTER`` bypasses the table
entirely (ops/sparse.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import time
from typing import Dict, List, Optional

import numpy as np

DEFAULT_TABLE = os.path.join(os.path.dirname(__file__), "sparse_dispatch.json")
ENV_TABLE = "OMLDM_SPARSE_SCATTER_TABLE"

# skip a kernel whose intermediate working set would not fit a modest host
# (the mxu one-hot operands are [2n, D/512 + 512] bf16 — at D=2^20 and
# n=160k that is >1 GB, pointless to measure on CPU and an OOM risk in CI)
MXU_BYTES_CAP = 1 << 28


def table_path() -> str:
    return os.environ.get(ENV_TABLE, "").strip() or DEFAULT_TABLE


_cache: Dict[str, object] = {"path": None, "mtime": None, "table": None}


def load_table(path: Optional[str] = None) -> Optional[dict]:
    """Cached table read (mtime-invalidated; None when absent/corrupt)."""
    path = path or table_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    if _cache["path"] == path and _cache["mtime"] == mtime:
        return _cache["table"]  # type: ignore[return-value]
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(table, dict) or "backends" not in table:
        return None
    _cache.update(path=path, mtime=mtime, table=table)
    return table


def lookup_winner(backend: str, d: int, n_updates: int) -> Optional[str]:
    """Winner at the nearest measured (D, updates) grid point for this
    backend — log2-space nearest neighbor, since both axes are decade
    scales. None when the backend has no measured section (callers fall
    back to the pre-calibration guess)."""
    table = load_table()
    if table is None:
        return None
    section = table.get("backends", {}).get(str(backend))
    if not section:
        return None
    entries = section.get("entries") or []
    best, best_dist = None, None
    ld, ln = math.log2(max(d, 1)), math.log2(max(n_updates, 1))
    for e in entries:
        try:
            dist = abs(math.log2(max(int(e["d"]), 1)) - ld) + abs(
                math.log2(max(int(e["updates"]), 1)) - ln
            )
            winner = str(e["winner"])
        except (KeyError, TypeError, ValueError):
            continue
        if best_dist is None or dist < best_dist:
            best, best_dist = winner, dist
    return best


# --- measurement -----------------------------------------------------------


def _gen_updates(d: int, batch: int, nnz: int, seed: int = 0):
    """Hashed-categorical update profile: each COO slot draws from its own
    ~1k-value vocabulary inside [0, d) — the duplicate structure of the
    Criteo/Avazu streams (benchmarks/run_benchmarks.py stream gen), which
    is exactly what the segsum pre-combine exists to exploit."""
    rng = np.random.RandomState(seed)
    vocab_n = min(1000, max(d // nnz, 2))
    idx = np.empty((batch, nnz), np.int32)
    for k in range(nnz):
        vocab = rng.randint(0, d, size=vocab_n)
        idx[:, k] = vocab[rng.randint(0, vocab_n, size=batch)]
    val = rng.randn(batch, nnz).astype(np.float32)
    coef = rng.randn(batch).astype(np.float32)
    return idx, val, coef


def _measure_kernel(fn, d: int, idx, val, coef, steps: int,
                    repeats: int = 3) -> float:
    """Updates/sec for one kernel: ``steps`` applications chained in ONE
    jitted scan (per-dispatch overhead would otherwise dominate through
    the TPU tunnel), w donated, best-of-``repeats``."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(w, ii, vv, cc):
        def body(ww, _):
            return fn(ww, ii, cc, vv), None

        w, _ = jax.lax.scan(body, w, None, length=steps)
        return w

    w = jnp.zeros((d,), jnp.float32)
    ii, vv, cc = jnp.asarray(idx), jnp.asarray(val), jnp.asarray(coef)
    chain(w, ii, vv, cc).block_until_ready()  # compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        chain(w, ii, vv, cc).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return steps * idx.size / best


def measure_entry(d: int, batch: int, nnz: int, steps: int) -> dict:
    from omldm_tpu.ops.sparse import MXU_LANES, SCATTER_IMPLS

    idx, val, coef = _gen_updates(d, batch, nnz)
    n = idx.size
    rates: Dict[str, Optional[float]] = {}
    for name, fn in SCATTER_IMPLS.items():
        if name == "mxu":
            r = -(-d // MXU_LANES)
            est = 2 * (2 * n) * (r + MXU_LANES)  # bf16 one-hot operands
            if est > MXU_BYTES_CAP:
                rates[name] = None
                continue
        rates[name] = round(_measure_kernel(fn, d, idx, val, coef, steps), 1)
    measured = {k: v for k, v in rates.items() if v is not None}
    winner = max(measured, key=measured.get)  # type: ignore[arg-type]
    dup = n / max(len(np.unique(idx)), 1)
    return {
        "d": d,
        "batch": batch,
        "nnz": nnz,
        "updates": n,
        "duplicate_factor": round(dup, 2),
        "rates_updates_per_sec": rates,
        "winner": winner,
    }


FULL_GRID = [
    (d, batch, nnz)
    for d in (1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20)
    for batch in (1024, 4096)
    for nnz in (8, 40)
]
# CI-sized: covers both sides of the guessed 2^16 crossover in seconds
SMOKE_GRID = [(1 << 12, 256, 8), (1 << 16, 256, 8), (1 << 18, 256, 8)]


def calibrate(grid: List[tuple], steps: int, out: Optional[str] = None,
              tag: str = "") -> dict:
    """Measure the grid on the CURRENT backend and merge the section into
    the table at ``out`` (other backends' sections are preserved)."""
    import jax

    backend = jax.default_backend()
    entries = []
    for d, batch, nnz in grid:
        e = measure_entry(d, batch, nnz, steps)
        entries.append(e)
        print(
            f"  d=2^{int(math.log2(d))} batch={batch} nnz={nnz} "
            f"dup={e['duplicate_factor']}x -> {e['winner']} "
            f"{e['rates_updates_per_sec']}"
        )
    out = out or table_path()
    table = load_table(out) or {
        "version": 1,
        "note": (
            "sparse scatter dispatch crossover table — generated by "
            "python -m omldm_tpu.ops.sparse_calibrate; "
            "sparse_scatter_add_auto (ops/sparse.py) reads the nearest "
            "(d, updates) entry for the active backend at trace time"
        ),
        "backends": {},
    }
    table["backends"][backend] = {
        "generated_by": (
            f"python -m omldm_tpu.ops.sparse_calibrate {tag}".strip()
        ),
        "steps_per_sample": steps,
        "entries": entries,
    }
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, out)
    _cache["path"] = None  # force reload on next lookup
    print(f"wrote {backend} section ({len(entries)} entries) -> {out}")
    return table


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--smoke", action="store_true",
        help="CI-sized grid: seconds, exercises the table format and both "
        "sides of the guessed crossover",
    )
    ap.add_argument("--out", default=None, help="table path (default: "
                    "$OMLDM_SPARSE_SCATTER_TABLE or ops/sparse_dispatch.json)")
    ap.add_argument("--steps", type=int, default=None,
                    help="chained kernel applications per timing sample")
    args = ap.parse_args(argv)
    grid = SMOKE_GRID if args.smoke else FULL_GRID
    steps = args.steps or (4 if args.smoke else 16)
    calibrate(grid, steps, out=args.out,
              tag="--smoke" if args.smoke else "")


if __name__ == "__main__":
    main()
