"""Ulysses-style sequence parallelism: all_to_all head/sequence re-shard.

The second of the two standard long-context strategies (alongside ring
attention, omldm_tpu.ops.ring_attention): instead of rotating K/V chunks
around the ring, ONE ``all_to_all`` re-shards the activations from
sequence-sharded ``[B, L/sp, H, Dh]`` to head-sharded ``[B, L, H/sp, Dh]``,
each device runs ordinary (flash/blockwise) attention over the FULL
sequence for its head group, and a second ``all_to_all`` restores sequence
sharding. Two collectives total per attention call — cheaper than ring's
sp-1 hops when heads divide evenly and the full-sequence activations fit —
while ring keeps O(L/sp) memory. ``TransformerConfig.seq_parallel`` picks
the strategy per model.

Requires ``n_heads % sp == 0``. Runs INSIDE ``shard_map`` with the
sequence dim sharded over ``axis_name``.
"""

from __future__ import annotations

import functools

import jax

from omldm_tpu.utils.jaxcompat import axis_size, shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from omldm_tpu.ops.attention import attention


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
) -> jnp.ndarray:
    """Per-shard Ulysses attention. q,k,v: the LOCAL chunk [B, Lc, H, Dh];
    returns the local chunk of the attention output [B, Lc, H, Dh]."""
    n = axis_size(axis_name)
    if n == 1:
        return attention(q, k, v, causal=causal)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"n_heads {h} not divisible by sp axis size {n}")

    def scatter_heads(x):
        # [B, Lc, H, Dh] -> [B, L, H/n, Dh]: split the head dim across the
        # axis, gather all sequence chunks (source-shard order = seq order)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        # [B, L, H/n, Dh] -> [B, Lc, H, Dh]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = attention(qg, kg, vg, causal=causal)
    return gather_heads(out)


def ulysses_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = False,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Whole-array convenience wrapper (testing): shards the sequence dim of
    [B, L, H, Dh] inputs over ``axis_name`` and runs Ulysses."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
