"""Attention kernels: reference, blockwise (flash-style), and Pallas TPU.

The reference has no attention anywhere (SURVEY.md section 2.4 — its models
are per-record online learners over feature vectors), but long-context
sequence models are first-class in this framework: the transformer family
(omldm_tpu.models.transformer) and sequence/context parallelism
(omldm_tpu.ops.ring_attention) are built on the kernels here.

Three implementations, one contract ``[B, L, H, Dh] -> [B, L, H, Dh]``:

- ``mha_reference``      — materializes the full [L, L] score matrix; O(L^2)
                           memory; ground truth for tests.
- ``blockwise_attention``— flash-style online-softmax over K/V blocks via
                           ``lax.scan``: O(L * block) memory, numerically
                           identical (up to fp assoc.) to the reference.
                           Works on every backend; this is also the
                           per-device inner loop of ring attention.
- ``flash_attention_pallas`` — hand-tiled Pallas TPU kernel keeping the
                           Q block + online-softmax accumulators in VMEM;
                           ``interpret=True`` runs it on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Plain softmax attention. q,k,v: [B, L, H, Dh].

    ``q_offset``/``kv_offset`` give the absolute positions of the first query
    / key row — used by the blockwise and ring variants to apply a causal
    mask across chunk boundaries."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(dh))
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])[:, None]
        ki = kv_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_k: int = 256,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Flash-style attention: scan over K/V blocks with online softmax.

    q,k,v: [B, L, H, Dh] (Lk may differ from Lq). Never materializes the
    [Lq, Lk] matrix; peak memory is O(Lq * block_k) per head."""
    b, lq, h, dh = q.shape
    lk = k.shape[1]
    block_k = min(block_k, lk)
    pad = (-lk) % block_k
    if pad:
        # padded keys are masked out via an explicit finite bias so that a
        # fully-masked block still produces well-defined (zero) weights
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = (lk + pad) // block_k
    kb = k.reshape(b, n_blocks, block_k, h, dh).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, n_blocks, block_k, h, dh).transpose(1, 0, 2, 3, 4)

    scale = 1.0 / jnp.sqrt(float(dh))
    q_pos = q_offset + jnp.arange(lq)
    # derive accumulators from q so that, under shard_map, they inherit its
    # varying-axis type (scan requires matching carry types)
    zq = jnp.transpose(q.astype(jnp.float32) * 0.0, (0, 2, 1, 3))  # [B,H,Lq,Dh]
    o0 = zq
    m0 = zq[..., 0] + NEG_INF
    l0 = zq[..., 0]

    def scan_step(carry, kv):
        o, m, l, step = carry
        kb_i, vb_i = kv
        if pad:
            # mask pad rows of the (only) ragged final block; the NEG_INF
            # bias alone suffices — p is exactly 0 for padded keys
            ki_local = step * block_k + jnp.arange(block_k)
            kbias = jnp.where(ki_local < lk, 0.0, NEG_INF)
        else:
            kbias = None
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kb_i.astype(jnp.float32)) * scale
        if kbias is not None:
            s = s + kbias[None, None, None, :]
        if causal:
            ki = kv_offset + step * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos[:, None] >= ki, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard: a row with every key masked so far (m_new still -inf) must
        # produce zero weights, not exp(0)=1 per masked key
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = alpha * l + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb_i.astype(jnp.float32)
        )
        return (o_new, m_new, l_new, step + 1), None

    (o, m, l, _), _ = jax.lax.scan(scan_step, (o0, m0, l0, jnp.int32(0)), (kb, vb))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Lq, H, Dh]


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  q_offset: int, kv_offset: int, lk: int):
    """Grid: (B*H, Lq/block_q). Each program owns one Q tile and sweeps all
    K/V blocks keeping the online-softmax accumulators in VMEM."""
    block_q, dh = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)  # [bq, dh]
    scale = 1.0 / jnp.sqrt(float(dh))

    n_blocks = pl.cdiv(lk, block_k)

    def body(j, carry):
        o, m, l = carry
        k = k_ref[pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        ki_local = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(ki_local < lk, s, NEG_INF)
        if causal:
            q_pos = (
                q_offset + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            s = jnp.where(q_pos >= kv_offset + ki_local, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # same fully-masked-row guard as the blockwise/ring variants
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[:, None]))
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = alpha * l + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, dh), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_blocks, body, (o0, m0, l0))
    o_ref[...] = (o / jnp.maximum(l[:, None], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "q_offset", "kv_offset", "interpret"),
)
def flash_attention_pallas(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    q_offset: int = 0,
    kv_offset: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas flash attention. q,k,v: [B, L, H, Dh] -> [B, Lq, H, Dh].

    The grid is (B*H, ceil(Lq/block_q)); K/V live in VMEM per (batch, head)
    program and are streamed block_k rows at a time through the MXU. Use
    ``interpret=True`` on CPU."""
    b, lq, h, dh = q.shape
    lk = k.shape[1]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    pad_q = (-lq) % block_q
    pad_k = (-lk) % block_k

    # flatten (B, H) into the leading grid axis; pallas BlockSpec tiles Lq
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, dh)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, dh)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, dh)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))

    # under shard_map's vma typing the kernel output must declare which mesh
    # axes it varies over — inherit the query's
    try:
        vma = jax.typeof(qf).vma
    except Exception:
        vma = None
    out_struct = (
        jax.ShapeDtypeStruct((b * h, lq + pad_q, dh), q.dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct((b * h, lq + pad_q, dh), q.dtype)
    )
    grid = (b * h, (lq + pad_q) // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            q_offset=q_offset,
            kv_offset=kv_offset,
            lk=lk,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, lk + pad_k, dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, lk + pad_k, dh), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, dh), lambda i, j: (i, j, 0)),
        out_shape=out_struct,
        interpret=interpret,
    )(qf, kf, vf)
    out = out[:, :lq].reshape(b, h, lq, dh).transpose(0, 2, 1, 3)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal: bool, q_offset: int, kv_offset: int,
                interpret: bool = False):
    """Differentiable wrapper: Pallas forward, blockwise-derived backward
    (flash backward recomputes attention anyway; the blockwise VJP is the
    same O(L * block) memory)."""
    return flash_attention_pallas(
        q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        interpret=interpret,
    )


def _flash_diff_fwd(q, k, v, causal, q_offset, kv_offset, interpret=False):
    out = flash_attention_pallas(
        q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        interpret=interpret,
    )
    return out, (q, k, v)


def _flash_diff_bwd(causal, q_offset, kv_offset, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset
        ),
        q, k, v,
    )
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
    block_k: int = 256,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """Backend-dispatching attention entry point: the Pallas kernel on TPU
    (differentiable via a blockwise-derived VJP), blockwise scan elsewhere."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return _flash_diff(q, k, v, causal, q_offset, kv_offset)
    return blockwise_attention(
        q, k, v, causal=causal, block_k=block_k,
        q_offset=q_offset, kv_offset=kv_offset,
    )
