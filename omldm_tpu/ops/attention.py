"""Attention kernels: reference, blockwise (flash-style), and Pallas TPU.

The reference has no attention anywhere (SURVEY.md section 2.4 — its models
are per-record online learners over feature vectors), but long-context
sequence models are first-class in this framework: the transformer family
(omldm_tpu.models.transformer) and sequence/context parallelism
(omldm_tpu.ops.ring_attention) are built on the kernels here.

Three implementations, one contract ``[B, L, H, Dh] -> [B, L, H, Dh]``:

- ``mha_reference``      — materializes the full [L, L] score matrix; O(L^2)
                           memory; ground truth for tests.
- ``blockwise_attention``— flash-style online-softmax over K/V blocks via
                           ``lax.scan``: O(L * block) memory, numerically
                           identical (up to fp assoc.) to the reference.
                           Works on every backend; this is also the
                           per-device inner loop of ring attention.
- ``flash_attention_pallas`` — hand-tiled Pallas TPU kernel keeping the
                           Q block + online-softmax accumulators in VMEM;
                           ``interpret=True`` runs it on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Plain softmax attention. q,k,v: [B, L, H, Dh].

    ``q_offset``/``kv_offset`` give the absolute positions of the first query
    / key row — used by the blockwise and ring variants to apply a causal
    mask across chunk boundaries."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(dh))
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])[:, None]
        ki = kv_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def online_softmax_sweep(
    q32: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    carry,
    q_pos: jnp.ndarray,
    kv_pos_start,
    causal: bool = False,
    block_k: int = 256,
):
    """Sweep ONE K/V chunk in key blocks, updating an online-softmax carry.

    q32: [B, Lq, H, Dh] float32; k/v: [B, Lk, H, Dh]; carry is
    ``(o [B,H,Lq,Dh], m [B,H,Lq], l [B,H,Lq])``. ``q_pos`` are absolute
    query positions [Lq]; ``kv_pos_start`` the absolute position of key row
    0 (may be a traced scalar — ring attention passes the rotating chunk's
    origin). Never materializes more than [.., Lq, block_k] scores —
    shared by :func:`blockwise_attention` and the per-hop accumulate of
    ring attention."""
    b, lq, h, dh = q32.shape
    lk = k.shape[1]
    block_k = min(block_k, lk)
    pad = (-lk) % block_k
    if pad:
        # padded keys are masked out via an explicit finite bias so that a
        # fully-masked block still produces well-defined (zero) weights
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = (lk + pad) // block_k
    kb = k.reshape(b, n_blocks, block_k, h, dh).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, n_blocks, block_k, h, dh).transpose(1, 0, 2, 3, 4)
    scale = 1.0 / jnp.sqrt(float(dh))

    def scan_step(c, kv):
        o, m, l, step = c
        kb_i, vb_i = kv
        ki_local = step * block_k + jnp.arange(block_k)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb_i.astype(jnp.float32)) * scale
        if pad:
            # mask pad rows of the (only) ragged final block; the NEG_INF
            # bias alone suffices — p is exactly 0 for padded keys
            s = jnp.where(ki_local[None, None, None, :] < lk, s, NEG_INF)
        if causal:
            ki = kv_pos_start + ki_local[None, :]
            s = jnp.where(q_pos[:, None] >= ki, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard: a row with every key masked so far (m_new still -inf) must
        # produce zero weights, not exp(0)=1 per masked key
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = alpha * l + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb_i.astype(jnp.float32)
        )
        return (o_new, m_new, l_new, step + 1), None

    o0, m0, l0 = carry
    (o, m, l, _), _ = jax.lax.scan(
        scan_step, (o0, m0, l0, jnp.int32(0)), (kb, vb)
    )
    return o, m, l


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_k: int = 256,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Flash-style attention: scan over K/V blocks with online softmax.

    q,k,v: [B, L, H, Dh] (Lk may differ from Lq). Never materializes the
    [Lq, Lk] matrix; peak memory is O(Lq * block_k) per head."""
    q32 = q.astype(jnp.float32)
    q_pos = q_offset + jnp.arange(q.shape[1])
    # derive accumulators from q so that, under shard_map, they inherit its
    # varying-axis type (scan requires matching carry types)
    zq = jnp.transpose(q32 * 0.0, (0, 2, 1, 3))  # [B,H,Lq,Dh]
    carry = (zq, zq[..., 0] + NEG_INF, zq[..., 0])
    o, m, l = online_softmax_sweep(
        q32, k, v, carry, q_pos, kv_offset, causal=causal, block_k=block_k
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Lq, H, Dh]


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  causal: bool, q_offset: int, kv_offset: int, lk: int,
                  n_k: int):
    """Grid: (B*H, Lq/block_q, Lk/block_k) with the K axis innermost
    (sequential). Each program sees ONE Q tile and ONE K/V tile; the
    online-softmax accumulators live in VMEM scratch and carry across the
    K sweep, so VMEM holds O(block_q * (dh + block_k)) regardless of Lk —
    the whole-K/V-per-program staging this replaces blew VMEM exactly in
    the long-context regime the module exists for."""
    block_q, dh = q_ref.shape
    block_k = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # skip K blocks entirely above the causal diagonal: the last query
        # row of this Q tile attends to nothing in them
        last_q_pos = q_offset + qi * block_q + (block_q - 1)
        first_k_pos = kv_offset + ki * block_k
        needed = last_q_pos >= first_k_pos
    else:
        needed = ki >= 0  # always

    @pl.when(needed)
    def _block():
        q = q_ref[...].astype(jnp.float32)  # [bq, dh]
        k = k_ref[...].astype(jnp.float32)  # [bk, dh]
        v = v_ref[...].astype(jnp.float32)
        scale = 1.0 / jnp.sqrt(float(dh))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        ki_local = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(ki_local < lk, s, NEG_INF)
        if causal:
            q_pos = (
                q_offset + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            s = jnp.where(q_pos >= kv_offset + ki_local, s, NEG_INF)
        m_prev = m_ref[...]  # [bq, 1]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # same fully-masked-row guard as the blockwise/ring variants
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        o_ref[...] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "q_offset", "kv_offset", "interpret"),
)
def flash_attention_pallas(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    q_offset: int = 0,
    kv_offset: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas flash attention. q,k,v: [B, L, H, Dh] -> [B, Lq, H, Dh].

    The grid is (B*H, ceil(Lq/block_q), ceil(Lk/block_k)) with the K axis
    sequential: VMEM holds one Q tile, one K/V tile and the online-softmax
    accumulators — O(block_q * (dh + block_k)) regardless of context
    length. Causal runs skip K tiles above the diagonal. 512/512 tiles
    measured fastest on TPU v5e (11 TFLOP/s causal at L=8192, 48x the
    lax blockwise scan). Use ``interpret=True`` on CPU."""
    b, lq, h, dh = q.shape
    lk = k.shape[1]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    pad_q = (-lq) % block_q
    pad_k = (-lk) % block_k

    # flatten (B, H) into the leading grid axis; pallas BlockSpec tiles Lq
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, dh)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, dh)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, dh)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))

    # under shard_map's vma typing the kernel output must declare which mesh
    # axes it varies over — inherit the query's
    try:
        vma = jax.typeof(qf).vma
    except Exception:
        vma = None
    out_struct = (
        jax.ShapeDtypeStruct((b * h, lq + pad_q, dh), q.dtype, vma=vma)
        if vma
        else jax.ShapeDtypeStruct((b * h, lq + pad_q, dh), q.dtype)
    )
    n_k = (lk + pad_k) // block_k
    grid = (b * h, (lq + pad_q) // block_q, n_k)
    scratch = [
        pltpu.VMEM((block_q, dh), jnp.float32),   # acc
        pltpu.VMEM((block_q, 1), jnp.float32),    # m (running max)
        pltpu.VMEM((block_q, 1), jnp.float32),    # l (running denom)
    ]
    kwargs = {}
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if not interpret and params_cls is not None:
        # the K axis carries the accumulators: sequential ("arbitrary");
        # B*H and the Q tiles are embarrassingly parallel
        kwargs["compiler_params"] = params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            causal=causal,
            q_offset=q_offset,
            kv_offset=kv_offset,
            lk=lk,
            n_k=n_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, dh), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_k, dh), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((None, block_k, dh), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, dh), lambda i, j, kk: (i, j, 0)),
        out_shape=out_struct,
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf)
    out = out[:, :lq].reshape(b, h, lq, dh).transpose(0, 2, 1, 3)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal: bool, q_offset: int, kv_offset: int,
                interpret: bool = False):
    """Differentiable wrapper: Pallas forward, blockwise-derived backward
    (flash backward recomputes attention anyway; the blockwise VJP is the
    same O(L * block) memory)."""
    return flash_attention_pallas(
        q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        interpret=interpret,
    )


def _flash_diff_fwd(q, k, v, causal, q_offset, kv_offset, interpret=False):
    out = flash_attention_pallas(
        q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        interpret=interpret,
    )
    return out, (q, k, v)


def _flash_diff_bwd(causal, q_offset, kv_offset, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset
        ),
        q, k, v,
    )
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
    block_k: int = 256,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """Backend-dispatching attention entry point: the Pallas kernel on TPU
    (differentiable via a blockwise-derived VJP), blockwise scan elsewhere."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return _flash_diff(q, k, v, causal, q_offset, kv_offset)
    return blockwise_attention(
        q, k, v, causal=causal, block_k=block_k,
        q_offset=q_offset, kv_offset=kv_offset,
    )
