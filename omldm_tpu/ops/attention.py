"""Attention kernels: reference, blockwise (flash-style), and Pallas TPU.

The reference has no attention anywhere (SURVEY.md section 2.4 — its models
are per-record online learners over feature vectors), but long-context
sequence models are first-class in this framework: the transformer family
(omldm_tpu.models.transformer) and sequence/context parallelism
(omldm_tpu.ops.ring_attention) are built on the kernels here.

Three implementations, one contract ``[B, L, H, Dh] -> [B, L, H, Dh]``:

- ``mha_reference``      — materializes the full [L, L] score matrix; O(L^2)
                           memory; ground truth for tests.
- ``blockwise_attention``— flash-style online-softmax over K/V blocks via
                           ``lax.scan``: O(L * block) memory, numerically
                           identical (up to fp assoc.) to the reference.
                           Works on every backend; this is also the
                           per-device inner loop of ring attention.
- ``flash_attention_pallas`` — hand-tiled Pallas TPU kernel keeping the
                           Q block + online-softmax accumulators in VMEM;
                           ``interpret=True`` runs it on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Default Pallas tile sizes. Forward and backward prefer different shapes
# on v5e (bf16, causal L=8192, dh=64 — benchmarks/tune_flash_blocks.py):
# the forward is fastest at 1024x1024, the dq/dkdv backward passes at
# 512x1024. Override per call via block_q/block_k.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
DEFAULT_BWD_BLOCK_Q = 512
DEFAULT_BWD_BLOCK_K = 1024


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Plain softmax attention. q,k,v: [B, L, H, Dh].

    ``q_offset``/``kv_offset`` give the absolute positions of the first query
    / key row — used by the blockwise and ring variants to apply a causal
    mask across chunk boundaries."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(dh))
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])[:, None]
        ki = kv_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def online_softmax_sweep(
    q32: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    carry,
    q_pos: jnp.ndarray,
    kv_pos_start,
    causal: bool = False,
    block_k: int = 256,
):
    """Sweep ONE K/V chunk in key blocks, updating an online-softmax carry.

    q32: [B, Lq, H, Dh] float32; k/v: [B, Lk, H, Dh]; carry is
    ``(o [B,H,Lq,Dh], m [B,H,Lq], l [B,H,Lq])``. ``q_pos`` are absolute
    query positions [Lq]; ``kv_pos_start`` the absolute position of key row
    0 (may be a traced scalar — ring attention passes the rotating chunk's
    origin). Never materializes more than [.., Lq, block_k] scores —
    shared by :func:`blockwise_attention` and the per-hop accumulate of
    ring attention."""
    b, lq, h, dh = q32.shape
    lk = k.shape[1]
    block_k = min(block_k, lk)
    pad = (-lk) % block_k
    if pad:
        # padded keys are masked out via an explicit finite bias so that a
        # fully-masked block still produces well-defined (zero) weights
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = (lk + pad) // block_k
    kb = k.reshape(b, n_blocks, block_k, h, dh).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, n_blocks, block_k, h, dh).transpose(1, 0, 2, 3, 4)
    scale = 1.0 / jnp.sqrt(float(dh))

    def scan_step(c, kv):
        o, m, l, step = c
        kb_i, vb_i = kv
        ki_local = step * block_k + jnp.arange(block_k)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb_i.astype(jnp.float32)) * scale
        if pad:
            # mask pad rows of the (only) ragged final block; the NEG_INF
            # bias alone suffices — p is exactly 0 for padded keys
            s = jnp.where(ki_local[None, None, None, :] < lk, s, NEG_INF)
        if causal:
            ki = kv_pos_start + ki_local[None, :]
            s = jnp.where(q_pos[:, None] >= ki, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard: a row with every key masked so far (m_new still -inf) must
        # produce zero weights, not exp(0)=1 per masked key
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = alpha * l + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb_i.astype(jnp.float32)
        )
        return (o_new, m_new, l_new, step + 1), None

    o0, m0, l0 = carry
    (o, m, l, _), _ = jax.lax.scan(
        scan_step, (o0, m0, l0, jnp.int32(0)), (kb, vb)
    )
    return o, m, l


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_k: int = 256,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Flash-style attention: scan over K/V blocks with online softmax.

    q,k,v: [B, L, H, Dh] (Lk may differ from Lq). Never materializes the
    [Lq, Lk] matrix; peak memory is O(Lq * block_k) per head."""
    q32 = q.astype(jnp.float32)
    q_pos = q_offset + jnp.arange(q.shape[1])
    # derive accumulators from q so that, under shard_map, they inherit its
    # varying-axis type (scan requires matching carry types)
    zq = jnp.transpose(q32 * 0.0, (0, 2, 1, 3))  # [B,H,Lq,Dh]
    carry = (zq, zq[..., 0] + NEG_INF, zq[..., 0])
    o, m, l = online_softmax_sweep(
        q32, k, v, carry, q_pos, kv_offset, causal=causal, block_k=block_k
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Lq, H, Dh]


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention kernel
# ---------------------------------------------------------------------------


def _masked_scores(q_ref, k_ref, qi, ki, *, causal, q_offset, kv_offset, lk):
    """Scaled QK^T for one (Q tile, K tile) pair with the K-padding and
    causal masks applied — the ONE implementation all three kernels
    (forward, dq, dk/dv) share so their masking can never diverge.

    The K-padding mask is STATICALLY skipped when Lk divides the tile
    evenly (no padded keys exist) — measured worthwhile. Runtime-
    conditional masking (lax.cond on a per-block scalar) was tried for the
    causal mask and REGRESSED ~40% on v5e: Mosaic serializes around the
    branch, costing more than the elementwise mask it saves. So the causal
    mask stays unconditional."""
    block_q, dh = q_ref.shape
    block_k = k_ref.shape[0]
    # operands keep their storage dtype: bf16 x bf16 -> f32 runs the MXU at
    # full rate (casting to f32 first halves/quarters it); accumulation is
    # always f32 via preferred_element_type
    q = q_ref[...]
    k = k_ref[...]
    scale = 1.0 / jnp.sqrt(float(dh))
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale

    need_pad_mask = lk % block_k != 0  # static: no padded keys otherwise
    if need_pad_mask or causal:
        ki_local = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if need_pad_mask:
            s = jnp.where(ki_local < lk, s, NEG_INF)
        if causal:
            q_pos = (
                q_offset + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            )
            s = jnp.where(q_pos >= kv_offset + ki_local, s, NEG_INF)
    return s, scale


def _causal_block_needed(qi, ki, block_q, block_k, q_offset, kv_offset):
    """A (Q tile, K tile) pair is skippable iff it lies entirely above the
    causal diagonal."""
    return (q_offset + qi * block_q + block_q - 1) >= (
        kv_offset + ki * block_k
    )


def _causal_kv_index(block_q, block_k, q_offset, kv_offset):
    """K/V BlockSpec index_map for causal grids (B*H, q tile j, k step kk):
    clamp the k index to the LAST needed tile for this Q tile. Skipped
    steps (kk past the diagonal) then map to the same block as the step
    before, and Mosaic elides the repeat DMA — pl.when alone skips the
    compute but still paid the HBM->VMEM copy for every masked block
    (~2x the needed K/V traffic at long context)."""

    def index_map(i, j, kk):
        last = (q_offset + (j + 1) * block_q - 1 - kv_offset) // block_k
        return (i, jnp.minimum(kk, jnp.maximum(last, 0)), 0)

    return index_map


def _causal_q_index(block_q, block_k, q_offset, kv_offset, n_q):
    """Q-side BlockSpec index_map for the dK/dV grid (B*H, k tile a, q step
    b_): clamp to the FIRST needed Q tile for this K tile (the skipped
    steps sit at the sweep's start), same DMA-elision trick as above."""

    def index_map(i, a, b_):
        first = (kv_offset + a * block_k - q_offset) // block_q
        first = jnp.minimum(jnp.maximum(first, 0), n_q - 1)
        return (i, jnp.maximum(b_, first), 0)

    return index_map


def _vma_struct_factory(ref_array):
    """ShapeDtypeStruct builder inheriting ``ref_array``'s varying-axis type
    (required for pallas_call outputs under shard_map's vma checking)."""
    try:
        vma = jax.typeof(ref_array).vma
    except Exception:
        vma = None

    def _struct(shape, dtype):
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        return jax.ShapeDtypeStruct(shape, dtype)

    return _struct


def _tpu_compiler_kwargs(interpret: bool) -> dict:
    """dimension_semantics for the canonical (parallel, parallel, arbitrary)
    flash grids, tolerant of the CompilerParams name moving across JAX
    versions."""
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if interpret or params_cls is None:
        return {}
    return {
        "compiler_params": params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    }


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, causal: bool, q_offset: int, kv_offset: int, lk: int,
                  n_k: int):
    """Grid: (B*H, Lq/block_q, Lk/block_k) with the K axis innermost
    (sequential). Each program sees ONE Q tile and ONE K/V tile; the
    online-softmax accumulators live in VMEM scratch and carry across the
    K sweep, so VMEM holds O(block_q * (dh + block_k)) regardless of Lk —
    the whole-K/V-per-program staging this replaces blew VMEM exactly in
    the long-context regime the module exists for."""
    block_q, dh = q_ref.shape
    block_k = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # skip K blocks entirely above the causal diagonal: the last query
        # row of this Q tile attends to nothing in them
        needed = _causal_block_needed(qi, ki, block_q, block_k,
                                      q_offset, kv_offset)
    else:
        needed = ki >= 0  # always

    @pl.when(needed)
    def _block():
        s, _ = _masked_scores(q_ref, k_ref, qi, ki, causal=causal,
                              q_offset=q_offset, kv_offset=kv_offset, lk=lk)
        # m/l scratch is LANES wide with every lane identical: subtracting
        # a [bq, 1] vector from the [bq, bk] scores broadcasts from lane 0,
        # which the VPU does poorly — pltpu.repeat of a full vreg is cheap
        # (the jax reference flash kernel's MIN_BLOCK_SIZE trick)
        m_prev = m_ref[...]  # [bq, LANES]
        l_prev = l_ref[...]
        lanes = m_prev.shape[-1]
        m_curr = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_curr)          # [bq, LANES]
        if block_k % lanes == 0 and block_k > lanes:
            m_rep = pltpu.repeat(m_new, block_k // lanes, axis=1)
        elif block_k <= lanes:
            m_rep = m_new[:, :block_k]
        else:  # ragged block_k (< full tiles): lane-0 broadcast fallback
            m_rep = jnp.broadcast_to(m_new[:, :1], s.shape)
        # same fully-masked-row guard as the blockwise/ring variants
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_rep))
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))  # [bq, LANES]
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        if dh > lanes and dh % lanes == 0:
            alpha_dh = pltpu.repeat(alpha, dh // lanes, axis=1)
        elif dh <= lanes:
            alpha_dh = alpha[:, :dh]
        else:  # ragged dh: lane-0 broadcast fallback
            alpha_dh = jnp.broadcast_to(alpha[:, :1], acc_ref.shape)
        # P quantizes to the value dtype for the PV matmul (bf16 MXU rate;
        # identity for f32 inputs) — the accumulator stays f32
        acc_ref[...] = acc_ref[...] * alpha_dh + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        lanes_f = l_ref.shape[-1]
        if dh > lanes_f and dh % lanes_f == 0:
            l_dh = pltpu.repeat(l_ref[...], dh // lanes_f, axis=1)
        elif dh <= lanes_f:
            l_dh = l_ref[:, :dh]
        else:
            l_dh = jnp.broadcast_to(l_ref[:, :1], acc_ref.shape)
        o_ref[...] = (
            acc_ref[...] / jnp.maximum(l_dh, 1e-30)
        ).astype(o_ref.dtype)
        # per-row logsumexp: the backward kernels recompute P from S - lse
        lse_ref[...] = (
            m_ref[:, :1] + jnp.log(jnp.maximum(l_ref[:, :1], 1e-30))
        )


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "q_offset", "kv_offset",
                     "interpret", "return_lse"),
)
def flash_attention_pallas(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
    interpret: bool = False,
    return_lse: bool = False,
):
    """Pallas flash attention. q,k,v: [B, L, H, Dh] -> [B, Lq, H, Dh].

    The grid is (B*H, ceil(Lq/block_q), ceil(Lk/block_k)) with the K axis
    sequential: VMEM holds one Q tile, one K/V tile and the online-softmax
    accumulators — O(block_q * (dh + block_k)) regardless of context
    length. Causal runs skip K tiles above the diagonal. 512/512 tiles
    measured fastest on TPU v5e (11 TFLOP/s causal at L=8192, 48x the
    lax blockwise scan). Use ``interpret=True`` on CPU."""
    b, lq, h, dh = q.shape
    lk = k.shape[1]
    block_q = min(block_q or DEFAULT_BLOCK_Q, lq)
    block_k = min(block_k or DEFAULT_BLOCK_K, lk)
    pad_q = (-lq) % block_q
    pad_k = (-lk) % block_k

    # flatten (B, H) into the leading grid axis; pallas BlockSpec tiles Lq
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, dh)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, dh)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, dh)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))

    # under shard_map's vma typing the kernel output must declare which mesh
    # axes it varies over — inherit the query's
    _struct = _vma_struct_factory(qf)
    out_struct = (
        _struct((b * h, lq + pad_q, dh), q.dtype),
        _struct((b * h, lq + pad_q, 1), jnp.float32),  # logsumexp rows
    )
    n_k = (lk + pad_k) // block_k
    grid = (b * h, (lq + pad_q) // block_q, n_k)
    # m/l scratch is a full 128-lane vreg wide (every lane identical): the
    # kernel expands it over the score block with pltpu.repeat instead of
    # a slow lane-0 broadcast
    lanes = 128
    scratch = [
        pltpu.VMEM((block_q, dh), jnp.float32),     # acc
        pltpu.VMEM((block_q, lanes), jnp.float32),  # m (running max)
        pltpu.VMEM((block_q, lanes), jnp.float32),  # l (running denom)
    ]
    # the K axis carries the accumulators: sequential ("arbitrary");
    # B*H and the Q tiles are embarrassingly parallel
    kwargs = _tpu_compiler_kwargs(interpret)
    kv_index = (
        _causal_kv_index(block_q, block_k, q_offset, kv_offset)
        if causal else (lambda i, j, kk: (i, kk, 0))
    )
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            causal=causal,
            q_offset=q_offset,
            kv_offset=kv_offset,
            lk=lk,
            n_k=n_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, dh), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_k, dh), kv_index),
            pl.BlockSpec((None, block_k, dh), kv_index),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, dh), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j, kk: (i, j, 0)),
        ),
        out_shape=out_struct,
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf)
    out, lse = out
    out = out[:, :lq].reshape(b, h, lq, dh).transpose(0, 2, 1, 3)
    if return_lse:
        return out, lse  # lse stays in the flattened [B*H, Lq+pad, 1] layout
    return out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, causal, q_offset, kv_offset,
                         lk, n_k):
    """dQ pass: grid (B*H, Lq/bq, Lk/bk), K sequential. Recomputes each
    score block from the saved per-row logsumexp (flash backward never
    materializes P) and accumulates dQ in VMEM scratch."""
    block_q, dh = q_ref.shape
    block_k = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if causal:
        needed = _causal_block_needed(qi, ki, block_q, block_k,
                                      q_offset, kv_offset)
    else:
        needed = ki >= 0

    @pl.when(needed)
    def _block():
        s, scale = _masked_scores(q_ref, k_ref, qi, ki, causal=causal,
                                  q_offset=q_offset, kv_offset=kv_offset,
                                  lk=lk)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse_ref[...]))
        # storage-dtype operands, f32 accumulators (see _masked_scores)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[...])
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[...] = acc_ref[...].astype(dq_ref.dtype)


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *, causal,
                           q_offset, kv_offset, lk, n_q):
    """dK/dV pass: grid (B*H, Lk/bk, Lq/bq), Q sequential. One K/V tile's
    gradients accumulate across the whole Q sweep in VMEM scratch."""
    block_q, dh = q_ref.shape
    block_k = k_ref.shape[0]
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        needed = _causal_block_needed(qi, ki, block_q, block_k,
                                      q_offset, kv_offset)
    else:
        needed = qi >= 0

    @pl.when(needed)
    def _block():
        s, scale = _masked_scores(q_ref, k_ref, qi, ki, causal=causal,
                                  q_offset=q_offset, kv_offset=kv_offset,
                                  lk=lk)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse_ref[...]))
        # storage-dtype operands, f32 accumulators (see _masked_scores)
        # dV += P^T dO
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[...])
        # dK += dS^T Q * scale
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)




@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal: bool, q_offset: int, kv_offset: int,
                interpret: bool = False):
    """Differentiable Pallas flash attention: Pallas forward AND backward
    (dq / dk-dv passes recompute scores from the saved logsumexp)."""
    return flash_attention_pallas(
        q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        interpret=interpret,
    )


def _flash_diff_fwd(q, k, v, causal, q_offset, kv_offset, interpret=False):
    out, lse = flash_attention_pallas(
        q, k, v, causal=causal, q_offset=q_offset, kv_offset=kv_offset,
        interpret=interpret, return_lse=True,
    )
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(causal, q_offset, kv_offset, interpret, res, g):
    q, k, v, out, lse = res
    b, lq, h, dh = q.shape
    lk = k.shape[1]
    block_q = min(DEFAULT_BWD_BLOCK_Q, lq)
    block_k = min(DEFAULT_BWD_BLOCK_K, lk)
    pad_q = (-lq) % block_q
    pad_k = (-lk) % block_k
    n_q = (lq + pad_q) // block_q
    n_k = (lk + pad_k) // block_k

    def flat(a, pad):
        f = a.transpose(0, 2, 1, 3).reshape(b * h, a.shape[1], dh)
        if pad:
            f = jnp.pad(f, ((0, 0), (0, pad), (0, 0)))
        return f

    qf, kf, vf = flat(q, pad_q), flat(k, pad_k), flat(v, pad_k)
    dof, of = flat(g, pad_q), flat(out, pad_q)
    # the forward saved lse under ITS q padding (fwd/bwd tile sizes may
    # differ); re-pad to this pass's layout. Zero pad rows are inert: the
    # cotangent is zero there, so every pad contribution cancels.
    lse = lse[:, :lq]
    if pad_q:
        lse = jnp.pad(lse, ((0, 0), (0, pad_q), (0, 0)))
    # delta_i = rowsum(dO * O) per query row — tiny elementwise op, fused
    # by XLA around the kernels
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, lq+pad, 1]

    # under shard_map's vma typing the kernel outputs must declare which
    # mesh axes they vary over — inherit the cotangent's (same as forward)
    _struct = _vma_struct_factory(dof)
    kwargs = _tpu_compiler_kwargs(interpret)
    q_spec = pl.BlockSpec((None, block_q, dh), lambda i, a, b_: (i, a, 0))
    row_spec = pl.BlockSpec((None, block_q, 1), lambda i, a, b_: (i, a, 0))
    # causal: clamp skipped K steps to the last needed tile so their DMA
    # is elided (see _causal_kv_index)
    kv_map = (
        _causal_kv_index(block_q, block_k, q_offset, kv_offset)
        if causal else (lambda i, a, b_: (i, b_, 0))
    )
    kv_spec = pl.BlockSpec((None, block_k, dh), kv_map)

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, causal=causal, q_offset=q_offset,
            kv_offset=kv_offset, lk=lk, n_k=n_k,
        ),
        grid=(b * h, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=_struct((b * h, lq + pad_q, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf, dof, lse, delta)

    # dK/dV pass: grid axes swap roles — a/b_ are (k tile, q tile). The
    # causal-skipped q steps sit at the sweep start; clamp their index to
    # the first needed tile (DMA elision again).
    q_map = (
        _causal_q_index(block_q, block_k, q_offset, kv_offset, n_q)
        if causal else (lambda i, a, b_: (i, b_, 0))
    )
    q_spec2 = pl.BlockSpec((None, block_q, dh), q_map)
    row_spec2 = pl.BlockSpec((None, block_q, 1), q_map)
    kv_spec2 = pl.BlockSpec((None, block_k, dh), lambda i, a, b_: (i, a, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, causal=causal, q_offset=q_offset,
            kv_offset=kv_offset, lk=lk, n_q=n_q,
        ),
        grid=(b * h, n_k, n_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(
            _struct((b * h, lk + pad_k, dh), k.dtype),
            _struct((b * h, lk + pad_k, dh), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, dh), jnp.float32),
            pltpu.VMEM((block_k, dh), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf, dof, lse, delta)

    def unflat(a, l):
        return a[:, :l].reshape(b, h, l, dh).transpose(0, 2, 1, 3)

    return unflat(dq, lq), unflat(dk, lk), unflat(dv, lk)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
    block_k: int = 256,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """Backend-dispatching attention entry point: the Pallas kernel on TPU
    (differentiable end to end — Pallas forward AND the dq / dk-dv backward
    kernels recomputing P from the saved logsumexp), blockwise scan
    elsewhere."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return _flash_diff(q, k, v, causal, q_offset, kv_offset)
    return blockwise_attention(
        q, k, v, causal=causal, block_k=block_k,
        q_offset=q_offset, kv_offset=kv_offset,
    )
