"""Sparse (padded-COO) feature ops for high-dimensional linear learners.

Reference counterpart: ``mlAPI.math.SparseVector`` — a first-class input
type in the reference's parse path (reference:
src/main/scala/omldm/utils/parsers/dataStream/DataPointParser.scala:4,20-47).
Criteo-class streams (13 numeric + 26 categoricals hashed into 2^18+) and
Avazu-class hashed streams must not densify through a fixed width: the
model weight vector stays dense on device (HBM is fine with a few MB), but
each record touches only its K active features.

TPU-first layout: a batch is ``(idx[B, K] int32, val[B, K] float32)`` with
FIXED K (max nnz per record, padded with idx=0/val=0 — a zero value
contributes nothing to either the gather-dot or the scatter-add, so pad
slots are harmless without sentinel bookkeeping). Static shapes keep XLA
happy; gathers/scatters lower to efficient dynamic-(update-)slice loops on
TPU and the surrounding elementwise work fuses.
"""

from __future__ import annotations

import jax.numpy as jnp

SparseBatch = tuple  # (idx[B, K] int32, val[B, K] float32)


def sparse_matvec(w: jnp.ndarray, idx: jnp.ndarray, val: jnp.ndarray) -> jnp.ndarray:
    """margins[b] = sum_k w[idx[b, k]] * val[b, k]  (gather-dot)."""
    return jnp.sum(jnp.take(w, idx, axis=0) * val, axis=1)


def sparse_matmat(W: jnp.ndarray, idx: jnp.ndarray, val: jnp.ndarray) -> jnp.ndarray:
    """logits[b, c] = sum_k W[idx[b, k], c] * val[b, k] for W[D, C]."""
    rows = jnp.take(W, idx, axis=0)            # [B, K, C]
    return jnp.einsum("bkc,bk->bc", rows, val)


def sparse_scatter_add(
    w: jnp.ndarray, idx: jnp.ndarray, coef: jnp.ndarray, val: jnp.ndarray
) -> jnp.ndarray:
    """w[idx[b, k]] += coef[b] * val[b, k] over the whole batch (duplicate
    indices accumulate, including the idx=0 pad slots whose val is 0)."""
    upd = (coef[:, None] * val).reshape(-1)
    return w.at[idx.reshape(-1)].add(upd)


def sparse_scatter_add_outer(
    W: jnp.ndarray, idx: jnp.ndarray, coef: jnp.ndarray, val: jnp.ndarray
) -> jnp.ndarray:
    """W[idx[b, k], :] += val[b, k] * coef[b, :] for W[D, C] (the rank-1
    per-record outer product of a multiclass gradient)."""
    b, k = idx.shape
    upd = val[:, :, None] * coef[:, None, :]   # [B, K, C]
    return W.at[idx.reshape(-1)].add(upd.reshape(b * k, -1))


def sparse_sq_norm(val: jnp.ndarray) -> jnp.ndarray:
    """||x_b||^2 per record (pad slots contribute 0)."""
    return jnp.sum(val * val, axis=1)


def append_bias_sparse(idx: jnp.ndarray, val: jnp.ndarray, bias_index: int):
    """Append the constant-1 bias slot (weight row ``bias_index``) to every
    record — the sparse analogue of learners.base.append_bias."""
    b = idx.shape[0]
    bias_idx = jnp.full((b, 1), bias_index, idx.dtype)
    bias_val = jnp.ones((b, 1), val.dtype)
    return (
        jnp.concatenate([idx, bias_idx], axis=1),
        jnp.concatenate([val, bias_val], axis=1),
    )
