"""Sparse (padded-COO) feature ops for high-dimensional linear learners.

Reference counterpart: ``mlAPI.math.SparseVector`` — a first-class input
type in the reference's parse path (reference:
src/main/scala/omldm/utils/parsers/dataStream/DataPointParser.scala:4,20-47).
Criteo-class streams (13 numeric + 26 categoricals hashed into 2^18+) and
Avazu-class hashed streams must not densify through a fixed width: the
model weight vector stays dense on device (HBM is fine with a few MB), but
each record touches only its K active features.

TPU-first layout: a batch is ``(idx[B, K] int32, val[B, K] float32)`` with
FIXED K (max nnz per record, padded with idx=0/val=0 — a zero value
contributes nothing to either the gather-dot or the scatter-add, so pad
slots are harmless without sentinel bookkeeping). Static shapes keep XLA
happy; gathers/scatters lower to efficient dynamic-(update-)slice loops on
TPU and the surrounding elementwise work fuses.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

SparseBatch = tuple  # (idx[B, K] int32, val[B, K] float32)


def sparse_matvec(w: jnp.ndarray, idx: jnp.ndarray, val: jnp.ndarray) -> jnp.ndarray:
    """margins[b] = sum_k w[idx[b, k]] * val[b, k]  (gather-dot)."""
    return jnp.sum(jnp.take(w, idx, axis=0) * val, axis=1)


def sparse_matmat(W: jnp.ndarray, idx: jnp.ndarray, val: jnp.ndarray) -> jnp.ndarray:
    """logits[b, c] = sum_k W[idx[b, k], c] * val[b, k] for W[D, C]."""
    rows = jnp.take(W, idx, axis=0)            # [B, K, C]
    return jnp.einsum("bkc,bk->bc", rows, val)


def sparse_scatter_add(
    w: jnp.ndarray, idx: jnp.ndarray, coef: jnp.ndarray, val: jnp.ndarray
) -> jnp.ndarray:
    """w[idx[b, k]] += coef[b] * val[b, k] over the whole batch (duplicate
    indices accumulate, including the idx=0 pad slots whose val is 0)."""
    upd = (coef[:, None] * val).reshape(-1)
    return w.at[idx.reshape(-1)].add(upd)


# lane width of the kron factorization below: the TPU register/MXU lane
# count, so the one-hot matmul operands tile exactly
MXU_LANES = 512


def sparse_scatter_add_mxu(
    w: jnp.ndarray, idx: jnp.ndarray, coef: jnp.ndarray, val: jnp.ndarray
) -> jnp.ndarray:
    """The SAME scatter-add as :func:`sparse_scatter_add`, reformulated as
    ONE MXU contraction — XLA's TPU scatter serializes randomly-indexed
    updates at ~66M/s (measured, benchmarks/sparse_scatter_experiment.py)
    while the systolic array is idle; this trades FLOPs for that
    serialization.

    Factor the index space D <= R*C as (hi, lo) = divmod(idx, C) with
    C = 512 lanes. The scattered delta, viewed as a [R, C] matrix, is a
    sum of rank-1 one-hot outer products — i.e. one matmul over the
    update dimension n:

        delta[hi, lo] = sum_n u_n * e(hi_n) (x) e(lo_n)
                      = OneHotHi[n, R]^T @ (OneHotLo[n, C] * u_n)

    Numerics: one-hot entries are exact in bf16; u is split
    u = bf16(u) + bf16(u - bf16(u)) and the two halves are CONCATENATED
    along the contraction dim. The high half's products are exact; the
    low-half residual is itself rounded to bf16, leaving a bounded
    ~2^-17 relative error per update ON TOP of the f32 accumulation
    reorder — close to, but not exactly, scatter-bit-equivalence (pinned
    to 2e-5 against the scatter by tests/test_sparse.py).

    Cost: 2 * 2 * R*C FLOPs per update — at D = 2^18 that is ~1 MFLOP
    per scattered update, so the MXU formulation pays for itself exactly
    when the chip's matmul rate beats 66M * 2^20 FLOP/s; see the
    experiment's roofline section for where the crossover lands.

    Reference counterpart: SparseVector updates in the reference's data
    model (DataPointParser.scala:4,20-47) — the reference applies them
    element-by-element on the JVM; this is the TPU-native form.
    """
    d = w.shape[0]
    c = MXU_LANES
    r = -(-d // c)
    n = idx.size
    flat_idx = idx.reshape(n)
    u = (coef[:, None] * val).reshape(n).astype(jnp.float32)
    hi = flat_idx // c
    lo = flat_idx % c
    one_hi = jax.nn.one_hot(hi, r, dtype=jnp.bfloat16)            # [n, R]
    lo_oh = jax.nn.one_hot(lo, c, dtype=jnp.float32)              # [n, C]
    u_hi = u.astype(jnp.bfloat16).astype(jnp.float32)
    u_lo = u - u_hi
    rhs = jnp.concatenate(
        [
            (lo_oh * u_hi[:, None]).astype(jnp.bfloat16),
            (lo_oh * u_lo[:, None]).astype(jnp.bfloat16),
        ],
        axis=0,
    )                                                              # [2n, C]
    lhs = jnp.concatenate([one_hi, one_hi], axis=0)                # [2n, R]
    delta = jax.lax.dot_general(
        lhs, rhs, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                              # [R, C]
    flat = delta.reshape(-1)
    return w + (flat[:d] if r * c != d else flat)


def sparse_scatter_add_segsum(
    w: jnp.ndarray, idx: jnp.ndarray, coef: jnp.ndarray, val: jnp.ndarray
) -> jnp.ndarray:
    """The SAME scatter-add with duplicate indices PRE-COMBINED by a sort +
    segmented sum before the scatter touches ``w``.

    Hashed categorical batches are duplicate-heavy: popular category values
    repeat across most records of a batch, so the B*K raw updates collapse
    onto far fewer distinct rows. XLA's TPU scatter serializes per update
    row; this formulation moves the duplicate work into a bitonic sort and
    a segment sum (both fully vectorized on TPU), leaving the scatter with
    one combined update per distinct index and inert (idx 0, val 0) pads
    for the rest — the module's standard padding convention.

    Shapes stay static: with R <= n distinct indices, run totals land
    compactly in the first R slots of an [n] array via sorted segment ids,
    and slots >= R scatter a zero onto row 0. Numerics: per-row totals are
    plain f32 sums of the row's updates (no prefix-difference
    cancellation); only the accumulation ORDER differs from the direct
    scatter, the same 2e-5 envelope as the MXU twin
    (tests/test_sparse.py).

    Reference counterpart: SparseVector updates applied element-by-element
    on the JVM (DataPointParser.scala:4,20-47); this is the dedup-first
    TPU-native form.
    """
    n = idx.size
    flat_idx = idx.reshape(n)
    u = (coef[:, None] * val).reshape(n).astype(jnp.float32)
    si, su = jax.lax.sort_key_val(flat_idx, u)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), si[1:] != si[:-1]]
    )
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1       # run id, sorted
    run_total = jax.ops.segment_sum(
        su, seg, num_segments=n, indices_are_sorted=True
    )                                                      # [n], first R real
    pos = jnp.arange(n, dtype=jnp.int32)
    # run start positions compacted to the front (pads sort to the tail)
    start_pos = jnp.sort(jnp.where(is_start, pos, n))
    real = start_pos < n
    run_idx = jnp.where(real, si[jnp.minimum(start_pos, n - 1)], 0)
    return w.at[run_idx].add(jnp.where(real, run_total, 0.0))


# ---------------------------------------------------------------------------
# scatter dispatch: calibration table + env/config override
# ---------------------------------------------------------------------------

SCATTER_IMPLS = {
    "scatter": sparse_scatter_add,
    "mxu": sparse_scatter_add_mxu,
    "segsum": sparse_scatter_add_segsum,
}

# env knob: OMLDM_SPARSE_SCATTER = scatter | mxu | segsum | auto ("auto" or
# unset reads the calibration table); config twin: dataStructure
# {"scatterImpl": "..."} on the sparse learner spec (learners pass impl=).
_ENV_KNOB = "OMLDM_SPARSE_SCATTER"


def _resolve_impl(d: int, n_updates: int, impl=None) -> str:
    """Trace-time dispatch decision, in precedence order: explicit config
    (``impl`` argument, from dataStructure.scatterImpl), the
    OMLDM_SPARSE_SCATTER env var, the persisted calibration table
    (ops/sparse_dispatch.json, nearest (D, updates) grid point for this
    backend), and only then the uncalibrated fallback: ``scatter``.

    The round-5 ``D >= 2^16 -> mxu`` TPU guess is RETIRED (never
    validated: every calibration attempt against this environment's TPU
    wedges in client init — the tunnel serializes and hangs, see
    ops/sparse_dispatch.json "tpu_status" — so the guessed crossover was
    a number nobody ever measured). An uncalibrated backend now gets the
    plain scatter, the only formulation with a measured record on every
    backend we have touched; the first real
    ``python -m omldm_tpu.ops.sparse_calibrate`` run on a reachable chip
    writes the table section that makes the mxu/segsum formulations
    eligible there. The physics behind the old guess still stands as a
    hypothesis (XLA's TPU scatter serializes at ~66M updates/s
    regardless of D, benchmarks/sparse_scatter_experiment.py, while the
    MXU reformulation costs ~2*2*D FLOPs per update), but a hypothesis
    is what the calibration table exists to test, not to hardcode. On
    CPU the committed table measures the plain scatter fastest through
    D = 2^18 (12-17M updates/s); at D = 2^20 the scatter drops to ~8M as
    the target array falls out of cache and the segsum pre-combine
    (~10M, D-independent) wins 3 of 4 grid points; the MXU formulation
    never wins off-TPU.
    """
    if impl:
        name = str(impl)
        if name not in SCATTER_IMPLS:
            raise ValueError(
                f"unknown sparse scatter impl {name!r}; "
                f"expected one of {sorted(SCATTER_IMPLS)} "
            )
        return name
    env = os.environ.get(_ENV_KNOB, "").strip().lower()
    if env and env != "auto":
        if env not in SCATTER_IMPLS:
            raise ValueError(
                f"{_ENV_KNOB}={env!r}: expected "
                f"{sorted(SCATTER_IMPLS) + ['auto']}"
            )
        return env
    from omldm_tpu.ops.sparse_calibrate import lookup_winner

    winner = lookup_winner(jax.default_backend(), d, n_updates)
    if winner is not None:
        return winner
    # uncalibrated backend: plain scatter until a real calibration run
    # writes this backend's table section (the round-5 D>=2^16 mxu guess
    # is retired — see the docstring)
    return "scatter"


def sparse_scatter_add_auto(
    w: jnp.ndarray,
    idx: jnp.ndarray,
    coef: jnp.ndarray,
    val: jnp.ndarray,
    impl: str = None,
) -> jnp.ndarray:
    """Calibrated dispatch (resolved at trace time) between the three
    scatter formulations; see :func:`_resolve_impl` for the precedence
    chain and the measured record behind the fallback guess."""
    name = _resolve_impl(int(w.shape[0]), int(idx.size), impl)
    return SCATTER_IMPLS[name](w, idx, coef, val)


def sparse_scatter_add_outer(
    W: jnp.ndarray, idx: jnp.ndarray, coef: jnp.ndarray, val: jnp.ndarray
) -> jnp.ndarray:
    """W[idx[b, k], :] += val[b, k] * coef[b, :] for W[D, C] (the rank-1
    per-record outer product of a multiclass gradient)."""
    b, k = idx.shape
    upd = val[:, :, None] * coef[:, None, :]   # [B, K, C]
    return W.at[idx.reshape(-1)].add(upd.reshape(b * k, -1))


def sparse_sq_norm(val: jnp.ndarray) -> jnp.ndarray:
    """||x_b||^2 per record (pad slots contribute 0)."""
    return jnp.sum(val * val, axis=1)


def append_bias_sparse(idx: jnp.ndarray, val: jnp.ndarray, bias_index: int):
    """Append the constant-1 bias slot (weight row ``bias_index``) to every
    record — the sparse analogue of learners.base.append_bias."""
    b = idx.shape[0]
    bias_idx = jnp.full((b, 1), bias_index, idx.dtype)
    bias_val = jnp.ones((b, 1), val.dtype)
    return (
        jnp.concatenate([idx, bias_idx], axis=1),
        jnp.concatenate([val, bias_val], axis=1),
    )
