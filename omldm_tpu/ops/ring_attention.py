"""Ring attention: exact long-context attention over an ``"sp"`` mesh axis.

Sequence/context parallelism has no counterpart in the reference (no
sequence dimension exists there, SURVEY.md section 5 "long-context"), but it
is first-class here: sequences longer than one chip's HBM are sharded over
the ``"sp"`` mesh axis, each device holds one contiguous chunk of Q/K/V, and
K/V chunks rotate around the ring via ``ppermute`` (one hop per step, riding
ICI) while every device accumulates its queries' attention with the online
softmax — compute overlaps communication, memory stays O(L / sp) per device,
and the result is bit-for-bit softmax attention (up to fp reassociation).

Call :func:`ring_attention` INSIDE ``shard_map`` with the sequence axis
sharded over ``axis_name``; :func:`ring_attention_sharded` wraps a whole
[B, L, H, Dh] batch for convenience/testing.
"""

from __future__ import annotations

import functools

import jax

from omldm_tpu.utils.jaxcompat import axis_size, shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from omldm_tpu.ops.attention import NEG_INF, online_softmax_sweep


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    block_k: int = 512,
) -> jnp.ndarray:
    """Per-shard ring attention. q,k,v: the LOCAL chunk [B, Lc, H, Dh];
    shard i owns absolute positions [i*Lc, (i+1)*Lc). Must run inside
    ``shard_map`` with the sequence dim sharded over ``axis_name``."""
    b, lc, h, dh = q.shape
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    q32 = q.astype(jnp.float32)
    q_pos = idx * lc + jnp.arange(lc)  # absolute query positions [Lc]

    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate(acc, kc, vc, src):
        """Online-softmax update of (o, m, l) against the chunk whose origin
        shard is ``src`` (absolute key positions src*Lc + [0, Lc)). The
        chunk is swept in block_k-sized key blocks — peak score memory is
        [B, H, Lc, block_k], not the full O(Lc^2) chunk pair."""
        return online_softmax_sweep(
            q32, kc, vc, acc, q_pos, src * lc, causal=causal,
            block_k=block_k,
        )

    # derive the zero accumulators from q so they inherit its device-varying
    # type (shard_map's vma checking requires the scan carry types to match)
    zq = jnp.transpose(q32 * 0.0, (0, 2, 1, 3))  # [B, H, Lc, Dh]
    acc0 = (zq, zq[..., 0] + NEG_INF, zq[..., 0])

    # step 0: the local chunk, no communication
    acc = accumulate(acc0, k, v, idx)

    def step(carry, t):
        acc, kc, vc = carry
        # rotate K/V one hop around the ring, then accumulate — exactly n-1
        # hops total, so no chunk travels back to its origin unused
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        src = jax.lax.rem(idx - t + n, n)  # origin shard of this chunk
        acc = accumulate(acc, kc, vc, src)
        return (acc, kc, vc), None

    if n > 1:
        (acc, _, _), _ = jax.lax.scan(step, (acc, k, v), jnp.arange(1, n))
    o, m, l = acc
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Lc, H, Dh]


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = False,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Whole-array convenience wrapper: shards the sequence dim of
    [B, L, H, Dh] inputs over ``axis_name`` of ``mesh`` and runs the ring."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
