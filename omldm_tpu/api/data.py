"""Data-plane records: ``DataInstance`` in, ``Prediction`` out.

Reference counterpart: ControlAPI's ``DataInstance`` POJO with
``{numericalFeatures[], discreteFeatures[], categoricalFeatures[], target,
operation in {training, forecasting}, isValid, metadata}``
(reference: src/main/scala/omldm/utils/parsers/dataStream/DataPointParser.scala:17-47,
src/main/scala/omldm/utils/deserializers/DataInstanceDeserializer.scala:24-33)
and the ``Prediction`` POJO forwarded verbatim to the predictions topic
(src/main/scala/omldm/job/FlinkLearning.scala:98-101,
src/main/scala/omldm/network/FlinkNetwork.scala:250-255).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Mapping, Optional, Sequence, Tuple

TRAINING = "training"
FORECASTING = "forecasting"

# End-of-stream marker records: the reference's DataInstanceParser drops a bare
# "EOS" string marker (DataInstanceParser.scala:14); we honor the same marker
# for file-replay tooling.
EOS = "EOS"


# slots: the serving plane materializes one DataInstance per emitted
# prediction on its hot path — slot-backed instances construct ~2x faster
# and every field here is declared up front anyway
@dataclasses.dataclass(slots=True)
class DataInstance:
    """One streaming record, either a training or a forecasting point.

    ``numerical_features`` are continuous values, ``discrete_features`` are
    integer-valued, ``categorical_features`` are strings (one-hot/hashed by
    preprocessors). ``target`` is present for labeled training data.
    Mirrors DataPointParser.scala:16-54 semantics: a record is usable when it
    has at least one feature; a training operation additionally requires a
    target to become a labeled point.
    """

    id: Optional[int] = None
    numerical_features: Optional[Sequence[float]] = None
    discrete_features: Optional[Sequence[int]] = None
    categorical_features: Optional[Sequence[str]] = None
    target: Optional[float] = None
    operation: str = TRAINING
    metadata: Optional[Mapping[str, Any]] = None

    def invalid_reason(self) -> Optional[str]:
        """Why this record fails the reference's ``isValid`` check
        (DataInstanceParser.scala:13-21), or None when usable. The reason
        code feeds the dead-letter sink (runtime/deadletter) so rejected
        records are quarantined with a cause instead of silently dropped."""
        if self.operation not in (TRAINING, FORECASTING):
            return "unknown_operation"
        has_features = any(
            f is not None and len(f) > 0
            for f in (
                self.numerical_features,
                self.discrete_features,
                self.categorical_features,
            )
        )
        if not has_features:
            return "no_features"
        # Python's json.loads accepts bare NaN/Infinity literals that the
        # reference's Jackson parser rejects; a single non-finite value would
        # poison model parameters, so reject them here.
        try:
            for f in (self.numerical_features, self.discrete_features):
                if f is not None and any(
                    v is None or not math.isfinite(v) for v in f
                ):
                    return "non_finite_feature"
            if self.target is not None and not math.isfinite(self.target):
                return "non_finite_target"
        except TypeError:
            # non-numeric feature elements (e.g. strings in numericalFeatures)
            return "non_numeric_feature"
        return None

    def is_valid(self) -> bool:
        """Validation mirroring the reference's ``isValid`` check applied in
        DataInstanceParser.scala:13-21: the record must carry features and a
        known operation."""
        return self.invalid_reason() is None

    @classmethod
    def forecast_payload(cls, numerical_features) -> "DataInstance":
        """Hot-path factory for the serving plane: the forecasting
        DataInstance a served prediction carries, built by direct slot
        fill. One such instance materializes per emitted prediction —
        at adaptive-batching throughput the generated ``__init__``'s
        seven keyword assignments are a measurable fraction of the whole
        serve path, and every field here is statically known."""
        di = cls.__new__(cls)
        di.id = None
        di.numerical_features = numerical_features
        di.discrete_features = None
        di.categorical_features = None
        di.target = None
        di.operation = FORECASTING
        di.metadata = None
        return di

    # --- JSON codec (Jackson-compatible camelCase field names) ---

    @classmethod
    def parse(
        cls, text: str
    ) -> Tuple[Optional["DataInstance"], Optional[str]]:
        """Parse a JSON record into ``(instance, rejection_reason)``.

        Exactly one of the pair is non-None, except for EOS markers and
        blank lines which return ``(None, None)`` — they are protocol
        markers (DataInstanceParser.scala:14), not malformed input, and
        must not be quarantined."""
        text = text.strip()
        if not text or text == EOS or text == f'"{EOS}"':
            return None, None
        try:
            obj = json.loads(text)
        except (json.JSONDecodeError, ValueError):
            return None, "malformed_json"
        if not isinstance(obj, dict):
            return None, "not_an_object"
        try:
            inst = cls.from_dict(obj)
        except (TypeError, ValueError):
            # e.g. non-numeric target: the reference's Jackson deserializer
            # fails and the record is dropped (DataInstanceDeserializer.scala:24-33)
            return None, "bad_field_type"
        reason = inst.invalid_reason()
        if reason is not None:
            return None, reason
        return inst, None

    @classmethod
    def from_json(cls, text: str) -> Optional["DataInstance"]:
        """Parse a JSON record; returns None for invalid records and the EOS
        marker, mirroring DataInstanceParser.scala:12-22 (drops invalid, drops
        "EOS", swallows parse errors)."""
        return cls.parse(text)[0]

    @classmethod
    def from_dict(cls, obj: Mapping[str, Any]) -> "DataInstance":
        target = obj.get("target")
        if target is not None:
            # non-numeric target => raise; from_json drops the record, matching
            # Jackson deserialization failure in the reference
            target = float(target)
        return cls(
            id=obj.get("id"),
            numerical_features=obj.get("numericalFeatures"),
            discrete_features=obj.get("discreteFeatures"),
            categorical_features=obj.get("categoricalFeatures"),
            target=target,
            operation=obj.get("operation", TRAINING),
            metadata=obj.get("metadata"),
        )

    def to_dict(self) -> dict:
        out: dict = {"operation": self.operation}
        if self.id is not None:
            out["id"] = self.id
        if self.numerical_features is not None:
            nf = self.numerical_features
            # the serving plane's batched emission carries feature rows as
            # numpy views (materializing per-row python lists would be the
            # single largest cost of a flush); tolist() lands the SAME
            # native-float JSON list() produces for list payloads
            out["numericalFeatures"] = (
                nf.tolist() if hasattr(nf, "tolist") else list(nf)
            )
        if self.discrete_features is not None:
            out["discreteFeatures"] = list(self.discrete_features)
        if self.categorical_features is not None:
            out["categoricalFeatures"] = list(self.categorical_features)
        if self.target is not None:
            out["target"] = self.target
        if self.metadata is not None:
            out["metadata"] = dict(self.metadata)
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


@dataclasses.dataclass(slots=True)
class Prediction:
    """A served prediction, emitted on the predictions stream.

    The reference forwards ControlAPI ``Prediction`` objects verbatim from the
    worker to the predictions Kafka topic (FlinkNetwork.scala:250-255,
    Job.scala:98-105)."""

    mlp_id: int
    data_instance: Optional[DataInstance]
    value: Any
    # model-lifecycle version tag (runtime/lifecycle.py): set ONLY on
    # canary-routed predictions served by a candidate version, so
    # operators (and the bitwise-identity gates) can separate candidate
    # output from the active version's. None — the default, and always
    # for lifecycle-unarmed pipelines — keeps the wire payload
    # byte-identical to the pre-plane format
    version: Optional[int] = None

    def to_dict(self) -> dict:
        out = {
            "mlpId": self.mlp_id,
            "dataInstance": self.data_instance.to_dict() if self.data_instance else None,
            "value": self.value,
        }
        if self.version is not None:
            out["version"] = self.version
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict())
