"""Query responses, possibly split into parameter buckets.

Reference counterpart: ControlAPI's ``QueryResponse`` ``{responseId,
id(bucket), mlpId, preprocessors, learner{parameters, hyperParameters,
dataStructure}, protocol, dataFitted, loss, cumulativeLoss, score}``
(reference: src/main/scala/omldm/network/FlinkNetwork.scala:196-231,
src/main/scala/omldm/utils/ResponseConstructor.scala:36-52). ``response_id ==
-1`` marks the internal termination probe (FlinkLearning.scala:115-133).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Optional, Sequence

# responseId used by the termination probe (FlinkLearning.scala:115-133).
TERMINATION_RESPONSE_ID = -1


@dataclasses.dataclass
class QueryResponse:
    response_id: int
    mlp_id: int
    bucket: int = 0  # the reference's `id` field: index of this param bucket
    num_buckets: int = 1
    preprocessors: Optional[Sequence[Mapping[str, Any]]] = None
    learner: Optional[Mapping[str, Any]] = None
    protocol: Optional[str] = None
    data_fitted: int = 0
    loss: Optional[float] = None
    cumulative_loss: Optional[float] = None
    score: Optional[float] = None
    # model-lifecycle observability (runtime/lifecycle.py): the worker's
    # registry view — active version, canary percentage, per-version
    # shadow scores — riding bucket-0 fragments of lifecycle-armed
    # pipelines; None (the default) keeps the pre-plane wire shape
    lifecycle: Optional[Mapping[str, Any]] = None
    # flight-recorder observability (runtime/events.py): the tail of the
    # per-pipeline event ring — the last few decision events tagged with
    # this pipeline — riding bucket-0 fragments when the recorder is
    # armed; None (the default) keeps the pre-plane wire shape
    events: Optional[Sequence[Mapping[str, Any]]] = None
    # internal routing metadata (NOT part of the wire format): which worker
    # emitted this fragment — lets the merger re-assemble parameter buckets
    # from a single replica's fragment set even when replicas differ
    # (async protocols between syncs)
    source_worker: Optional[int] = None

    @classmethod
    def from_dict(cls, obj: Mapping[str, Any]) -> "QueryResponse":
        return cls(
            response_id=int(obj["responseId"]),
            mlp_id=int(obj.get("mlpId", -1)),
            bucket=int(obj.get("id", 0)),
            num_buckets=int(obj.get("numBuckets", 1)),
            preprocessors=obj.get("preprocessors"),
            learner=obj.get("learner"),
            protocol=obj.get("protocol"),
            data_fitted=int(obj.get("dataFitted", 0)),
            loss=obj.get("loss"),
            cumulative_loss=obj.get("cumulativeLoss"),
            score=obj.get("score"),
            lifecycle=obj.get("lifecycle"),
            events=obj.get("events"),
        )

    def to_dict(self) -> dict:
        out = {
            "responseId": self.response_id,
            "id": self.bucket,
            "numBuckets": self.num_buckets,
            "mlpId": self.mlp_id,
            "preprocessors": self.preprocessors,
            "learner": self.learner,
            "protocol": self.protocol,
            "dataFitted": self.data_fitted,
            "loss": self.loss,
            "cumulativeLoss": self.cumulative_loss,
            "score": self.score,
        }
        if self.lifecycle is not None:
            out["lifecycle"] = dict(self.lifecycle)
        if self.events is not None:
            out["events"] = [dict(e) for e in self.events]
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict())
