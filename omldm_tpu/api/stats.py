"""Training statistics and the final job report.

Reference counterpart: ControlAPI's ``Statistics`` ``{pipeline, protocol,
modelsShipped, bytesShipped, numOfBlocks, fitted, learningCurve, LCX,
meanBufferSize, score}`` with ``updateStats/updateFitted/updateScore/
updateMeanBufferSize`` (reference:
src/main/scala/omldm/operators/hub/FlinkHub.scala:118-153,
src/main/scala/omldm/utils/statistics/StatisticsOperator.scala:96-125,
src/main/scala/omldm/state/StateAccumulators.scala:62-124) and
``JobStatistics(jobName, parallelism, durationMs, Statistics[])``
(StatisticsOperator.scala:110-127).
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Tuple


@dataclasses.dataclass
class Statistics:
    """Per-pipeline protocol + accuracy statistics.

    ``learning_curve`` is a list of (loss, #fitted) points — the reference
    slices it incrementally out of the PS on each stats poll
    (FlinkHub.scala:101-116,131-142); ``lcx`` is the matching x-axis
    (#records-fitted checkpoints)."""

    pipeline: int
    protocol: str = ""
    models_shipped: int = 0
    bytes_shipped: int = 0
    # bytes that actually crossed the hub<->spoke wire, counted per
    # MESSAGE at the transport boundary (ship wrappers / Hub.receive):
    # encoded payload sizes when a codec is configured (runtime.codec),
    # raw sizes otherwise. With codec none this matches bytes_shipped for
    # pure model-push traffic, but can differ slightly for protocols
    # whose control replies are not logically counted (e.g. SSP release
    # messages) — the wire counter sees every message, the logical one
    # only the reference's getSize call sites
    bytes_on_wire: int = 0
    num_of_blocks: int = 0
    # reliable-channel resilience counters (zero on the default
    # exactly-once in-process route): duplicate deliveries dropped by a
    # receive window, sequence gaps that triggered a NACK/resync cycle,
    # and barrier releases taken on a quorum while a silent worker was
    # retired from round accounting (runtime/messages.ReceiveWindow,
    # protocols/base.HubNode liveness)
    duplicates_dropped: int = 0
    gaps_resynced: int = 0
    quorum_releases: int = 0
    # jitted XLA program launches dispatched on this pipeline's behalf on
    # the host plane (fit / fit_many / predict / evaluate, plus ONE count
    # per shared cohort gang launch, attributed to the triggering member
    # so the cross-pipeline SUM equals real program launches); counted
    # spoke-side and folded in at query/terminate time
    program_launches: int = 0
    # tenant-mesh width GAUGE (JobConfig.cohort_shards): the device shard
    # count the pipeline's cohort launches ran across — 0 when sharding
    # is off/never engaged, max-combined (not summed) across contributors
    # so BENCH rounds can attribute throughput to mesh width
    cohort_shards: int = 0
    # model-integrity guard counters (zero with trainingConfiguration.guard
    # unset, the default): worker updates the hub-side admission boundary
    # rejected before round accounting (non-finite / norm-exploded),
    # last-known-good rollbacks the worker-side guard performed, and
    # cohort members evicted to solo execution after a divergence trip
    # (omldm_tpu.guard; protocols/base.HubNode.guard_admit, runtime/spoke)
    deltas_rejected: int = 0
    rollbacks_performed: int = 0
    members_evicted: int = 0
    # malformed / validation-rejected records routed to the dead-letter
    # sink instead of being silently dropped (runtime/deadletter). The
    # count is JOB-level (a dropped record would have reached every
    # pipeline) and is mirrored into each pipeline's statistics at
    # terminate — it does NOT sum across pipelines to the record count
    records_quarantined: int = 0
    # forecast serving telemetry (runtime/serving.py): predictions emitted
    # on this pipeline's behalf, and the enqueue->emit latency percentile
    # triple (ms) folded in from the spokes' per-record serving clocks —
    # populated by BOTH the immediate per-record path and the adaptive-
    # batching serving plane. Percentiles max-combine across contributors
    # (merge reports the worst observed window, a conservative summary)
    forecasts_served: int = 0
    serve_latency_p50_ms: float = 0.0
    serve_latency_p99_ms: float = 0.0
    serve_latency_p999_ms: float = 0.0
    # overload-control counters (runtime/overload.py; zero with the plane
    # unarmed, the default): forecasts shed with explicit reason-coded
    # dead-letter entries instead of queueing (CRITICAL pressure,
    # over-limit tenant), training rows deferred behind healthy tenants'
    # work (ELEVATED pressure), the worst pressure level the pipeline's
    # spokes reached (a GAUGE: 0 OK / 1 ELEVATED / 2 CRITICAL,
    # max-combined), and the p99 of enqueue->shed waits (ms, max-combined
    # like the serve-latency percentiles)
    forecasts_shed: int = 0
    records_throttled: int = 0
    pressure_level: int = 0
    shed_latency_ms: float = 0.0
    # model-lifecycle counters (runtime/lifecycle.py; zero with the plane
    # unarmed, the default): holdout shadow evaluations performed on a
    # candidate version, canary ramps that auto-promoted, rollbacks
    # (guard trips, score-envelope regressions, operator Rollbacks), and
    # the live model-version id — a GAUGE (0 = the Create-time model;
    # last-write per fold so a rollback moves it back down, max-combined
    # only across same-probe worker replicas in merge)
    shadow_scored: int = 0
    canary_promotions: int = 0
    canary_rollbacks: int = 0
    active_version: int = 0
    # elastic-rescale telemetry (runtime/job.StreamJob.rescale and the
    # distributed restore-with-rescale path, runtime/distributed_job):
    # ``rescales_performed`` counts parallelism changes the pipeline's
    # state has been carried across (live rescales in-process, restore-
    # with-rescale relaunches in the supervised deployment — a JOB-level
    # count mirrored into each pipeline's report like
    # ``records_quarantined``); ``fleet_processes`` is a GAUGE carrying
    # the CURRENT worker-process count of the distributed fleet (0 on the
    # in-process runtime, whose parallelism already rides JobStatistics)
    rescales_performed: int = 0
    fleet_processes: int = 0
    # self-healing fleet (runtime/selfheal.py + runtime/supervisor.py):
    # ``fleet_degraded`` is a GAUGE carrying how many process slots the
    # supervisor has shrunk away from the configured width after repeated
    # classified failures (0 = full width; pinned by the supervisor's
    # --fleetDegraded passthrough, mirrored job-wide like
    # ``fleet_processes``); ``blackbox_write_errors`` counts telemetry/
    # quarantine writes the disk refused (black-box ring dumps, dead-letter
    # file appends, heartbeat files) that degraded to a dropped-write
    # counter instead of killing the worker (ENOSPC survival) — a
    # job-level mirror, max-combined like events_recorded
    fleet_degraded: int = 0
    blackbox_write_errors: int = 0
    # flight-recorder telemetry (runtime/events.py; zero with the plane
    # unarmed, the default): decision events recorded in the job's
    # journal and watchdog alerts raised. JOB-level counts mirrored into
    # each pipeline's report at terminate (the records_quarantined
    # pattern) — max-combined in merge so cross-hub folds do not multiply
    events_recorded: int = 0
    alerts_raised: int = 0
    # transport-codec wall time (runtime/codec.py TransportCodec): total
    # encode/decode seconds spent preparing this pipeline's wire traffic,
    # folded once per contributor (spoke nets at query/terminate, hub
    # shards at terminate) — previously only visible on the codec objects
    # themselves, invisible in any report. Additive across contributors
    # (each owns its own codec clock).
    codec_encode_seconds: float = 0.0
    codec_decode_seconds: float = 0.0
    # launch-dispatch percentile GAUGES (utils/tracing.StepTimer rings):
    # per-launch ms for the fit flush path and the serving predict path,
    # folded from the spokes' timers at query/terminate and max-combined
    # across contributors (the same conservative worst-window summary as
    # the serve-latency percentiles)
    launch_p50_ms: float = 0.0
    launch_p99_ms: float = 0.0
    serve_launch_p50_ms: float = 0.0
    serve_launch_p99_ms: float = 0.0
    fitted: int = 0
    learning_curve: List[float] = dataclasses.field(default_factory=list)
    lcx: List[int] = dataclasses.field(default_factory=list)
    mean_buffer_size: float = 0.0
    score: float = 0.0

    def update_stats(
        self,
        models_shipped: int = 0,
        bytes_shipped: int = 0,
        num_of_blocks: int = 0,
        bytes_on_wire: int = 0,
        duplicates_dropped: int = 0,
        gaps_resynced: int = 0,
        quorum_releases: int = 0,
        program_launches: int = 0,
        deltas_rejected: int = 0,
        rollbacks_performed: int = 0,
        members_evicted: int = 0,
        records_quarantined: int = 0,
        forecasts_served: int = 0,
        cohort_shards: int = 0,
        forecasts_shed: int = 0,
        records_throttled: int = 0,
        pressure_level: int = 0,
        shadow_scored: int = 0,
        canary_promotions: int = 0,
        canary_rollbacks: int = 0,
        active_version: Optional[int] = None,
        rescales_performed: int = 0,
        fleet_processes: int = 0,
        fleet_degraded: int = 0,
        blackbox_write_errors: int = 0,
        codec_encode_seconds: float = 0.0,
        codec_decode_seconds: float = 0.0,
        events_recorded: int = 0,
        alerts_raised: int = 0,
    ) -> None:
        """Accumulate communication counters (FlinkHub.scala:118-127).
        ``cohort_shards`` and ``pressure_level`` are gauges: max-combined,
        not summed. ``active_version`` is a LAST-WRITE gauge: each fold
        carries the registry's CURRENT live version (None = this fold says
        nothing about it), so an operator rollback to version 0 really
        moves the reported value back down — a max would pin the
        historical peak forever."""
        self.models_shipped += models_shipped
        self.bytes_shipped += bytes_shipped
        self.num_of_blocks += num_of_blocks
        self.bytes_on_wire += bytes_on_wire
        self.duplicates_dropped += duplicates_dropped
        self.gaps_resynced += gaps_resynced
        self.quorum_releases += quorum_releases
        self.program_launches += program_launches
        self.deltas_rejected += deltas_rejected
        self.rollbacks_performed += rollbacks_performed
        self.members_evicted += members_evicted
        self.records_quarantined += records_quarantined
        self.forecasts_served += forecasts_served
        self.cohort_shards = max(self.cohort_shards, cohort_shards)
        self.forecasts_shed += forecasts_shed
        self.records_throttled += records_throttled
        self.pressure_level = max(self.pressure_level, pressure_level)
        self.shadow_scored += shadow_scored
        self.canary_promotions += canary_promotions
        self.canary_rollbacks += canary_rollbacks
        if active_version is not None:
            self.active_version = active_version
        self.rescales_performed += rescales_performed
        self.fleet_processes = max(self.fleet_processes, fleet_processes)
        self.fleet_degraded = max(self.fleet_degraded, fleet_degraded)
        self.blackbox_write_errors = max(
            self.blackbox_write_errors, blackbox_write_errors
        )
        self.codec_encode_seconds += codec_encode_seconds
        self.codec_decode_seconds += codec_decode_seconds
        # job-level mirrors (every fold carries the journal's current
        # totals): last-write-the-max, not sum, so the heartbeat peek +
        # terminate fold cannot double-count
        self.events_recorded = max(self.events_recorded, events_recorded)
        self.alerts_raised = max(self.alerts_raised, alerts_raised)

    def note_launch_ms(self, p50: float, p99: float) -> None:
        """Fold one contributor's fit-flush launch percentile window in
        (max-combine, the serve-latency convention)."""
        self.launch_p50_ms = max(self.launch_p50_ms, p50)
        self.launch_p99_ms = max(self.launch_p99_ms, p99)

    def note_serve_launch_ms(self, p50: float, p99: float) -> None:
        """Fold one contributor's serving-launch percentile window in."""
        self.serve_launch_p50_ms = max(self.serve_launch_p50_ms, p50)
        self.serve_launch_p99_ms = max(self.serve_launch_p99_ms, p99)

    def note_serve_latency(self, p50: float, p99: float, p999: float) -> None:
        """Fold one contributor's serving-latency percentile window in
        (max-combine: the report carries the worst observed percentiles
        across spokes/hubs — percentiles are not additive and shipping
        raw samples through statistics messages would defeat the point
        of a bounded telemetry plane)."""
        self.serve_latency_p50_ms = max(self.serve_latency_p50_ms, p50)
        self.serve_latency_p99_ms = max(self.serve_latency_p99_ms, p99)
        self.serve_latency_p999_ms = max(self.serve_latency_p999_ms, p999)

    def note_shed_latency(self, p99: float) -> None:
        """Fold one contributor's enqueue->shed p99 in (max-combine, same
        conservative summary as the serve-latency percentiles)."""
        self.shed_latency_ms = max(self.shed_latency_ms, p99)

    def update_fitted(self, fitted: int) -> None:
        self.fitted += fitted

    def update_score(self, score: float) -> None:
        self.score = score

    def update_mean_buffer_size(self, mbs: float) -> None:
        self.mean_buffer_size = mbs

    def extend_curve(self, points: List[Tuple[float, int]]) -> None:
        """Append incremental learning-curve slices (FlinkHub.scala:101-116)."""
        for loss, fitted in points:
            self.learning_curve.append(float(loss))
            self.lcx.append(int(fitted))

    def normalize(self, count: int) -> None:
        """Divide accumulated score / mean-buffer-size by the number of
        contributors, mirroring the statistics operator's end-of-job
        normalization over parallelism (StatisticsOperator.scala:100-125)."""
        if count > 0:
            self.score /= count
            self.mean_buffer_size /= count

    def merge(self, other: "Statistics") -> "Statistics":
        """Cross-hub merge: sums counters, concatenates learning curves in
        x order (StateAccumulators.scala:54-126).

        ``score`` and ``mean_buffer_size`` are *accumulated* here and must be
        normalized by the contributor count before reporting — the reference
        does the same accumulate-then-normalize over parallelism
        (StatisticsOperator.scala:109-125); call :meth:`normalize`."""
        assert self.pipeline == other.pipeline
        merged = Statistics(
            pipeline=self.pipeline,
            protocol=self.protocol or other.protocol,
            models_shipped=self.models_shipped + other.models_shipped,
            bytes_shipped=self.bytes_shipped + other.bytes_shipped,
            bytes_on_wire=self.bytes_on_wire + other.bytes_on_wire,
            num_of_blocks=self.num_of_blocks + other.num_of_blocks,
            duplicates_dropped=self.duplicates_dropped + other.duplicates_dropped,
            gaps_resynced=self.gaps_resynced + other.gaps_resynced,
            quorum_releases=self.quorum_releases + other.quorum_releases,
            program_launches=self.program_launches + other.program_launches,
            cohort_shards=max(self.cohort_shards, other.cohort_shards),
            deltas_rejected=self.deltas_rejected + other.deltas_rejected,
            rollbacks_performed=self.rollbacks_performed
            + other.rollbacks_performed,
            members_evicted=self.members_evicted + other.members_evicted,
            records_quarantined=self.records_quarantined
            + other.records_quarantined,
            forecasts_served=self.forecasts_served + other.forecasts_served,
            forecasts_shed=self.forecasts_shed + other.forecasts_shed,
            records_throttled=self.records_throttled
            + other.records_throttled,
            pressure_level=max(self.pressure_level, other.pressure_level),
            shed_latency_ms=max(self.shed_latency_ms, other.shed_latency_ms),
            shadow_scored=self.shadow_scored + other.shadow_scored,
            canary_promotions=self.canary_promotions
            + other.canary_promotions,
            canary_rollbacks=self.canary_rollbacks + other.canary_rollbacks,
            active_version=max(self.active_version, other.active_version),
            # a job-level mirror (every contributor reports the same
            # value): max-combine, not sum, so cross-hub merges do not
            # multiply the count
            rescales_performed=max(
                self.rescales_performed, other.rescales_performed
            ),
            fleet_processes=max(self.fleet_processes, other.fleet_processes),
            fleet_degraded=max(self.fleet_degraded, other.fleet_degraded),
            blackbox_write_errors=max(
                self.blackbox_write_errors, other.blackbox_write_errors
            ),
            events_recorded=max(
                self.events_recorded, other.events_recorded
            ),
            alerts_raised=max(self.alerts_raised, other.alerts_raised),
            codec_encode_seconds=self.codec_encode_seconds
            + other.codec_encode_seconds,
            codec_decode_seconds=self.codec_decode_seconds
            + other.codec_decode_seconds,
            launch_p50_ms=max(self.launch_p50_ms, other.launch_p50_ms),
            launch_p99_ms=max(self.launch_p99_ms, other.launch_p99_ms),
            serve_launch_p50_ms=max(
                self.serve_launch_p50_ms, other.serve_launch_p50_ms
            ),
            serve_launch_p99_ms=max(
                self.serve_launch_p99_ms, other.serve_launch_p99_ms
            ),
            serve_latency_p50_ms=max(
                self.serve_latency_p50_ms, other.serve_latency_p50_ms
            ),
            serve_latency_p99_ms=max(
                self.serve_latency_p99_ms, other.serve_latency_p99_ms
            ),
            serve_latency_p999_ms=max(
                self.serve_latency_p999_ms, other.serve_latency_p999_ms
            ),
            fitted=self.fitted + other.fitted,
            mean_buffer_size=self.mean_buffer_size + other.mean_buffer_size,
            score=self.score + other.score,
        )
        pairs = sorted(
            list(zip(self.lcx, self.learning_curve))
            + list(zip(other.lcx, other.learning_curve)),
            key=lambda p: p[0],
        )
        merged.lcx = [x for x, _ in pairs]
        merged.learning_curve = [y for _, y in pairs]
        return merged

    def to_dict(self) -> dict:
        return {
            "pipeline": self.pipeline,
            "protocol": self.protocol,
            "modelsShipped": self.models_shipped,
            "bytesShipped": self.bytes_shipped,
            "bytesOnWire": self.bytes_on_wire,
            "duplicatesDropped": self.duplicates_dropped,
            "gapsResynced": self.gaps_resynced,
            "quorumReleases": self.quorum_releases,
            "programLaunches": self.program_launches,
            "cohortShards": self.cohort_shards,
            "deltasRejected": self.deltas_rejected,
            "rollbacksPerformed": self.rollbacks_performed,
            "membersEvicted": self.members_evicted,
            "recordsQuarantined": self.records_quarantined,
            "forecastsServed": self.forecasts_served,
            "forecastsShed": self.forecasts_shed,
            "recordsThrottled": self.records_throttled,
            "pressureLevel": self.pressure_level,
            "shedLatencyMs": self.shed_latency_ms,
            "shadowScored": self.shadow_scored,
            "canaryPromotions": self.canary_promotions,
            "canaryRollbacks": self.canary_rollbacks,
            "activeVersion": self.active_version,
            "rescalesPerformed": self.rescales_performed,
            "fleetProcesses": self.fleet_processes,
            "fleetDegraded": self.fleet_degraded,
            "blackboxWriteErrors": self.blackbox_write_errors,
            "eventsRecorded": self.events_recorded,
            "alertsRaised": self.alerts_raised,
            "codecEncodeSeconds": self.codec_encode_seconds,
            "codecDecodeSeconds": self.codec_decode_seconds,
            "launchP50Ms": self.launch_p50_ms,
            "launchP99Ms": self.launch_p99_ms,
            "serveLaunchP50Ms": self.serve_launch_p50_ms,
            "serveLaunchP99Ms": self.serve_launch_p99_ms,
            "serveLatencyP50Ms": self.serve_latency_p50_ms,
            "serveLatencyP99Ms": self.serve_latency_p99_ms,
            "serveLatencyP999Ms": self.serve_latency_p999_ms,
            "numOfBlocks": self.num_of_blocks,
            "fitted": self.fitted,
            "learningCurve": self.learning_curve,
            "LCX": self.lcx,
            "meanBufferSize": self.mean_buffer_size,
            "score": self.score,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


@dataclasses.dataclass
class JobStatistics:
    """Final job report shipped to the performance stream
    (StatisticsOperator.scala:110-127, PerformanceWriter.scala:6-8)."""

    job_name: str
    parallelism: int
    duration_ms: float
    statistics: List[Statistics] = dataclasses.field(default_factory=list)
    # continuous-heartbeat extensions (runtime/telemetry.py): ``kind`` is
    # None on the terminate-time final report — whose wire shape then
    # stays EXACTLY the pre-telemetry schema — and "heartbeat" on the
    # incremental snapshots the armed telemetry plane emits mid-stream,
    # which also carry their beat ``seq`` and the plane's registry /
    # queue-depth / phase-table extras (merged top-level into to_dict).
    kind: Optional[str] = None
    seq: Optional[int] = None
    extra: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {
            "jobName": self.job_name,
            "parallelism": self.parallelism,
            "durationMs": self.duration_ms,
            "statistics": [s.to_dict() for s in self.statistics],
        }
        if self.kind is not None:
            d["kind"] = self.kind
            d["seq"] = self.seq
            for k, v in (self.extra or {}).items():
                d.setdefault(k, v)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def __str__(self) -> str:  # PerformanceWriter stringification
        return self.to_json()
