"""Control-plane requests: Create / Update / Query / Delete pipelines.

Reference counterpart: ControlAPI's ``Request`` POJO ``{id, request,
requestId, learner{name, parameters, hyperParameters, dataStructure},
preProcessors[], trainingConfiguration{protocol, HubParallelism, ...}}``
(reference: src/main/scala/omldm/utils/parsers/requestStream/PipelineMap.scala:22-47,
src/main/scala/omldm/operators/spoke/FlinkSpoke.scala:141-171,184,203-215,
src/main/scala/omldm/utils/deserializers/RequestDeserializer.scala:22-31).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Mapping, Optional, Sequence


class RequestType(str, enum.Enum):
    CREATE = "Create"
    UPDATE = "Update"
    QUERY = "Query"
    DELETE = "Delete"
    # model-lifecycle verbs (runtime/lifecycle.py; no reference
    # counterpart — the reference's only rollout primitive is the
    # destructive Update, PipelineMap.scala:43-47): Shadow registers a
    # candidate model configuration that trains + scores on the live
    # stream without serving; Promote starts (or completes) the canary
    # traffic ramp; Rollback demotes the candidate — or, after a
    # promotion, reactivates the retained previous version
    SHADOW = "Shadow"
    PROMOTE = "Promote"
    ROLLBACK = "Rollback"


# the lifecycle verb subset (validated and routed together)
LIFECYCLE_REQUESTS = (
    RequestType.SHADOW,
    RequestType.PROMOTE,
    RequestType.ROLLBACK,
)


@dataclasses.dataclass
class LearnerSpec:
    """Learner descriptor inside a request (PipelineMap.scala:26-29).

    ``name`` must be in the learner allowlist (PipelineMap.scala:68);
    ``hyper_parameters`` configure the update rule (e.g. PA's C, pegasos
    lambda); ``parameters`` optionally seed the model state; ``data_structure``
    carries learner-specific structural config (e.g. NN layer sizes, RFF dims).
    """

    name: str
    parameters: Optional[Mapping[str, Any]] = None
    hyper_parameters: Optional[Mapping[str, Any]] = None
    data_structure: Optional[Mapping[str, Any]] = None

    @classmethod
    def from_dict(cls, obj: Mapping[str, Any]) -> "LearnerSpec":
        return cls(
            name=obj["name"],
            parameters=obj.get("parameters"),
            hyper_parameters=obj.get("hyperParameters"),
            data_structure=obj.get("dataStructure"),
        )

    def to_dict(self) -> dict:
        out: dict = {"name": self.name}
        if self.parameters is not None:
            out["parameters"] = dict(self.parameters)
        if self.hyper_parameters is not None:
            out["hyperParameters"] = dict(self.hyper_parameters)
        if self.data_structure is not None:
            out["dataStructure"] = dict(self.data_structure)
        return out


@dataclasses.dataclass
class PreprocessorSpec:
    """Preprocessor descriptor (the reference's ``PreprocessorPOJO``,
    PipelineMap.scala:26-29); ``name`` must be in the preprocessor allowlist
    (PipelineMap.scala:67)."""

    name: str
    parameters: Optional[Mapping[str, Any]] = None
    hyper_parameters: Optional[Mapping[str, Any]] = None

    @classmethod
    def from_dict(cls, obj: Mapping[str, Any]) -> "PreprocessorSpec":
        return cls(
            name=obj["name"],
            parameters=obj.get("parameters"),
            hyper_parameters=obj.get("hyperParameters"),
        )

    def to_dict(self) -> dict:
        out: dict = {"name": self.name}
        if self.parameters is not None:
            out["parameters"] = dict(self.parameters)
        if self.hyper_parameters is not None:
            out["hyperParameters"] = dict(self.hyper_parameters)
        return out


@dataclasses.dataclass
class TrainingConfiguration:
    """Per-pipeline training configuration carried by the request
    (FlinkSpoke.scala:184,203-215, MLNodeGenerator.scala:22-43).

    ``protocol`` selects one of the 8 distributed-learning protocols;
    ``hub_parallelism`` (the reference's ``HubParallelism`` key,
    FlinkSpoke.scala:181-195) shards the parameter server; ``mini_batch_size``
    and ``per_record`` pick micro-batched vs exact per-record update semantics
    on TPU; protocol-specific knobs (staleness bound, EASGD alpha, GM/FGM
    threshold) ride in ``extra``.
    """

    protocol: str = "Asynchronous"
    hub_parallelism: int = 1
    mini_batch_size: Optional[int] = None
    per_record: bool = False
    extra: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, obj: Optional[Mapping[str, Any]]) -> "TrainingConfiguration":
        if not obj:
            return cls()
        known = {"protocol", "HubParallelism", "hubParallelism", "miniBatchSize", "perRecord"}
        extra = {k: v for k, v in obj.items() if k not in known}
        # knobs may arrive flat (the wire shape: unknown keys ARE the extra
        # map) or under an explicit "extra" object (the dataclass field
        # name, natural for programmatic construction via to_dict/asdict
        # round trips) — merge the nested form instead of burying it at
        # extra["extra"] where every lookup would miss it
        nested = extra.pop("extra", None)
        if isinstance(nested, Mapping):
            extra = {**nested, **extra}
        return cls(
            protocol=obj.get("protocol", "Asynchronous"),
            hub_parallelism=int(
                obj.get("HubParallelism", obj.get("hubParallelism", 1)) or 1
            ),
            mini_batch_size=obj.get("miniBatchSize"),
            per_record=bool(obj.get("perRecord", False)),
            extra=extra,
        )

    def to_dict(self) -> dict:
        out: dict = {
            "protocol": self.protocol,
            "HubParallelism": self.hub_parallelism,
        }
        if self.mini_batch_size is not None:
            out["miniBatchSize"] = self.mini_batch_size
        if self.per_record:
            out["perRecord"] = True
        out.update(self.extra)
        return out


@dataclasses.dataclass
class Request:
    """A control-plane request targeting pipeline ``id`` (the networkId)."""

    id: int
    request: RequestType
    request_id: Optional[int] = None
    learner: Optional[LearnerSpec] = None
    preprocessors: Sequence[PreprocessorSpec] = dataclasses.field(default_factory=list)
    training_configuration: TrainingConfiguration = dataclasses.field(
        default_factory=TrainingConfiguration
    )

    @classmethod
    def from_json(cls, text: str) -> Optional["Request"]:
        """JSON -> Request, mirroring RequestParser.scala:12-17 (drops
        malformed requests silently)."""
        try:
            obj = json.loads(text)
            return cls.from_dict(obj)
        except (json.JSONDecodeError, KeyError, ValueError, TypeError):
            return None

    @classmethod
    def from_dict(cls, obj: Mapping[str, Any]) -> "Request":
        return cls(
            id=int(obj["id"]),
            request=RequestType(obj["request"]),
            request_id=obj.get("requestId"),
            learner=LearnerSpec.from_dict(obj["learner"]) if obj.get("learner") else None,
            preprocessors=[
                PreprocessorSpec.from_dict(p) for p in obj.get("preProcessors") or []
            ],
            training_configuration=TrainingConfiguration.from_dict(
                obj.get("trainingConfiguration")
            ),
        )

    def to_dict(self) -> dict:
        out: dict = {"id": self.id, "request": self.request.value}
        if self.request_id is not None:
            out["requestId"] = self.request_id
        if self.learner is not None:
            out["learner"] = self.learner.to_dict()
        if self.preprocessors:
            out["preProcessors"] = [p.to_dict() for p in self.preprocessors]
        out["trainingConfiguration"] = self.training_configuration.to_dict()
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict())
