"""External JSON contract of the framework (the reference's ControlAPI POJOs).

The framework keeps the reference's external contract: JSON ``DataInstance`` /
``Request`` records in; ``Prediction`` / ``QueryResponse`` / ``JobStatistics``
out (SURVEY.md section 2.2, reference usage sites cited per class).
"""

from omldm_tpu.api.data import DataInstance, Prediction
from omldm_tpu.api.requests import (
    LearnerSpec,
    PreprocessorSpec,
    Request,
    RequestType,
    TrainingConfiguration,
)
from omldm_tpu.api.responses import QueryResponse
from omldm_tpu.api.stats import JobStatistics, Statistics

__all__ = [
    "DataInstance",
    "Prediction",
    "LearnerSpec",
    "PreprocessorSpec",
    "Request",
    "RequestType",
    "TrainingConfiguration",
    "QueryResponse",
    "Statistics",
    "JobStatistics",
]
