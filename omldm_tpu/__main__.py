"""CLI entry point: ``python -m omldm_tpu [--flag value ...]``.

Reference counterpart: ``Job.main(args)`` (reference:
src/main/scala/omldm/Job.scala:110-171) — parse ``--key value`` CLI flags
with ``ParameterTool.fromArgs`` semantics (Job.scala:114), build the sources
and sinks, assemble the job graph, and run it. The reference's flag surface
(README.md:28-41) is per-topic Kafka name+broker pairs plus the job knobs
(``parallelism``, ``test``, ``maxMsgParams``, ``jobName``, ``timeout``,
``testSetSize``, ``checkpointing``, ``checkInterval``, ``stateBackend``);
all job knobs are accepted here with the same names (JobConfig.from_args).

Sources (choose one style):

- ``--trainingData path.jsonl`` / ``--forecastingData path.jsonl`` /
  ``--requests path.jsonl`` — JSON-lines file replay, round-robin
  interleaved (the deterministic stand-in for stream union, Job.scala:70);
  ``EOS`` marker lines are dropped and replay continues, matching the
  reference parser (DataInstanceParser.scala:13-21).
- ``--events combined.jsonl`` — one fully-ordered file of
  ``{"stream": "trainingData"|"forecastingData"|"requests", "data": {...}}``
  lines, when the exact arrival order matters (e.g. Query after training).
- ``--kafkaBrokers host:port`` — live Kafka consumer/producer via
  omldm_tpu.runtime.kafka_io (requires kafka-python; silence-timer
  termination as in StatisticsOperator.scala:135-142).

Sinks: ``--predictionsOut`` / ``--responsesOut`` / ``--performanceOut``
write JSON lines to files (default: performance to stdout, mirroring the
reference's PerformanceWriter -> performance topic, FlinkLearning.scala:137-144).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, Iterator, List, Optional, Tuple

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.ingest import file_events, interleave
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    PACKED_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
    StreamJob,
)

_STREAMS = (TRAINING_STREAM, FORECASTING_STREAM, REQUEST_STREAM)


def parse_flags(argv: List[str]) -> Dict[str, str]:
    """``--key value`` pairs -> dict (ParameterTool.fromArgs, Job.scala:114).
    A flag without a value is treated as boolean true."""
    flags: Dict[str, str] = {}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if not arg.startswith("--"):
            raise SystemExit(f"expected --flag, got {arg!r}")
        key = arg[2:]
        if i + 1 < len(argv) and not argv[i + 1].startswith("--"):
            flags[key] = argv[i + 1]
            i += 2
        else:
            flags[key] = "true"
            i += 1
    return flags


def combined_events(path: str) -> Iterator[Tuple[str, str]]:
    """Replay a fully-ordered combined event file: each line is
    ``{"stream": <topic>, "data": <record object or JSON string>}``."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            stream = obj.get("stream")
            if stream not in _STREAMS:
                continue
            data = obj.get("data")
            yield (stream, data if isinstance(data, str) else json.dumps(data))


class _FileSink:
    def __init__(self, path: Optional[str], default=None):
        self._f = open(path, "w") if path else default

    def __call__(self, obj: Any) -> None:
        if self._f is None:
            return
        payload = obj.to_json() if hasattr(obj, "to_json") else json.dumps(obj)
        self._f.write(payload + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None and self._f not in (sys.stdout, sys.stderr):
            self._f.close()


def build_job(flags: Dict[str, str]) -> Tuple[StreamJob, List[_FileSink]]:
    config = JobConfig.from_args(flags)
    pred_sink = _FileSink(flags.get("predictionsOut"))
    resp_sink = _FileSink(flags.get("responsesOut"))
    perf_sink = _FileSink(flags.get("performanceOut"), default=sys.stdout)
    job = StreamJob(
        config,
        on_prediction=pred_sink,
        on_response=resp_sink,
        on_performance=perf_sink,
    )
    return job, [pred_sink, resp_sink, perf_sink]


def _ensure_backend() -> None:
    """Fall back to the CPU backend when the configured accelerator can't
    initialize (e.g. the TPU tunnel is down) instead of crashing the job."""
    import jax

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        jax.devices()


def _enable_compile_cache(flags: Dict[str, str]) -> None:
    """Persistent XLA compilation cache: first TPU compiles cost tens of
    seconds; caching them on disk makes every later job launch start hot.
    ``--compileCache off`` disables; ``--compileCache <dir>`` relocates
    (default ~/.cache/omldm_tpu/xla)."""
    import os

    cache = flags.get(
        "compileCache",
        os.path.join(os.path.expanduser("~"), ".cache", "omldm_tpu", "xla"),
    )
    if cache == "off":
        return
    import jax

    try:
        # parse BEFORE any config.update: a bad value must leave the cache
        # fully disabled, not half-configured
        min_secs = float(flags.get("compileCacheMinSecs", "1.0"))
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_secs
        )
    except Exception as exc:  # cache is an optimization, never fatal
        print(f"warning: compile cache disabled ({exc})", file=sys.stderr)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    flags = parse_flags(argv)
    if any(
        k in flags
        for k in ("processes", "processId", "coordinator", "supervise")
    ):
        # multi-process deployment: one entry point for both shapes
        # (Job.scala:110-120 — the reference has exactly one main); each
        # process runs the same command with its own --processId
        from omldm_tpu.runtime.distributed_job import run_distributed

        return run_distributed(argv)
    _ensure_backend()
    _enable_compile_cache(flags)
    job, sinks = build_job(flags)
    from omldm_tpu.utils import trace

    try:
        if "kafkaBrokers" in flags:
            # unbounded stream: the kafka loop bounds its own profile window
            # (--profileSteps events) instead of tracing the job lifetime
            return _run(job, flags)
        with trace(flags.get("profileDir")):
            return _run(job, flags)
    finally:
        for sink in sinks:
            sink.close()


def _run(job: StreamJob, flags: Dict[str, str]) -> int:
    if "kafkaBrokers" in flags:
        return _run_kafka(job, flags)
    elif "events" in flags:
        _run_replay(job, flags, lambda: combined_events(flags["events"]))
    else:
        if _try_fused_run(job, flags):
            return 0

        def make_events():
            packed = None
            if (
                TRAINING_STREAM in flags
                and flags.get("fastIngest", "auto") != "false"
            ):
                packed = _packed_training_source(flags)
            sources = []
            for topic in _STREAMS:
                if topic not in flags:
                    continue
                if topic == TRAINING_STREAM and packed is not None:
                    sources.append(packed)
                else:
                    sources.append(file_events(flags[topic], topic))
            if not sources:
                raise SystemExit(
                    "no sources: pass --trainingData/--forecastingData/"
                    "--requests <path.jsonl>, --events <combined.jsonl>, "
                    "or --kafkaBrokers <host:port>"
                )
            return interleave(*sources)

        _run_replay(job, flags, make_events)
    return 0


def _apply_kafka_sinks(job: StreamJob, flags: Dict[str, str], producer_sinks) -> None:
    """Kafka producers are the default egress; an explicitly-passed file
    sink keeps precedence over the producer for its stream."""
    job.set_sinks(
        on_prediction=(
            None if "predictionsOut" in flags else producer_sinks.on_prediction
        ),
        on_response=(
            None if "responsesOut" in flags else producer_sinks.on_response
        ),
        on_performance=(
            None if "performanceOut" in flags else producer_sinks.on_performance
        ),
    )
    # quarantined records/requests publish to the deadLetters topic in
    # addition to the job's in-memory ring / --deadLetterPath file
    job.dead_letter.publish = producer_sinks.on_dead_letter


def _kafka_loop(job: StreamJob, events, flags: Dict[str, str], profile: Dict) -> None:
    """One supervised attempt at the live polling loop. ``profile`` carries
    the bounded trace-window state across restart attempts (the window
    counts TOTAL events, and tracing stops exactly once)."""
    # start the silence clock at loop entry so a broker that never
    # delivers anything still terminates after the timeout
    job.stats.mark_activity()
    for event in events:  # yields None on each idle poll window
        if event is not None:
            job.process_event(*event)
            if job.checkpoint_manager is not None:
                job.checkpoint_manager.maybe_save(job)
            profile["n_events"] += 1
            if profile["tracing"] and profile["n_events"] >= profile["steps"]:
                import jax

                jax.profiler.stop_trace()
                profile["tracing"] = False
        else:
            # idle / backpressure-paused poll window: idle capacity decays
            # the overload counters so a CRITICAL pause can clear (no-op
            # when the plane is unarmed)
            job.overload_idle_tick()
        job.check_silence()
        if job.stats.terminated:
            break


def _kafka_retry_policies(flags: Dict[str, str]):
    """(connect/metadata policy, producer-send policy) from the CLI knobs
    ``--retry{Attempts,BaseDelayMs,Growth,JitterMs,TimeoutMs}`` and
    ``--sendRetry{...}`` (defaults in kafka_io)."""
    import dataclasses

    from omldm_tpu.runtime.kafka_io import CONNECT_RETRY, SEND_RETRY
    from omldm_tpu.utils.backoff import BackoffPolicy

    connect = BackoffPolicy.from_flags(
        flags, "retry", **dataclasses.asdict(CONNECT_RETRY)
    )
    send = BackoffPolicy.from_flags(
        flags, "sendRetry", **dataclasses.asdict(SEND_RETRY)
    )
    return connect, send


def _run_kafka(job: StreamJob, flags: Dict[str, str]) -> int:
    """The live Kafka job, optionally supervised (--restartAttempts N):
    on failure, restore the latest checkpoint taken during this run and
    seek the rebuilt consumer to the snapshot's (topic, partition) offsets
    — Flink's restore-from-checkpoint with Kafka source offsets. Without a
    usable snapshot the incarnation restarts fresh from the live position
    (no replay), Flink's uncheckpointed behavior on a live source. The
    restart loop itself runs under the shared backoff helper (fixed delay,
    bounded attempts — RestartStrategies.fixedDelayRestart)."""
    from omldm_tpu.runtime.kafka_io import connect_kafka
    from omldm_tpu.utils.backoff import with_backoff

    attempts = int(flags.get("restartAttempts", "0"))
    delay_s = float(flags.get("restartDelayMs", "0")) / 1000.0
    connect_retry, send_retry = _kafka_retry_policies(flags)
    # bounded profile window for the unbounded stream: trace only the
    # first --profileSteps events (default 1000)
    profile = {
        "tracing": False,
        "n_events": 0,
        "steps": int(flags.get("profileSteps", "1000")),
    }
    if flags.get("profileDir"):
        import jax

        jax.profiler.start_trace(flags["profileDir"])
        profile["tracing"] = True

    manager = job.checkpoint_manager
    ckpt_floor = manager.latest_path() if manager is not None else None
    tracker: Dict = {}
    # upstream backpressure (runtime/overload.py): while any spoke's
    # overload controller reports CRITICAL, the polling loop stops
    # consuming — offsets stay uncommitted, so paused traffic replays
    # instead of buffering. The indirection survives restarts (recovery
    # swaps the job object).
    pause_ref = {"job": job}
    _pause_when = lambda: pause_ref["job"].overload_level() >= 2  # noqa: E731
    events, producer_sinks = connect_kafka(
        flags["kafkaBrokers"], tracker=tracker,
        retry=connect_retry, send_retry=send_retry,
        pause_when=_pause_when,
    )
    # mutable attempt state: each restart swaps in the recovered job and
    # the reconnected clients for the next with_backoff attempt
    state = {"job": job, "events": events, "sinks": producer_sinks,
             "tracker": tracker}

    def _attempt() -> int:
        j = state["job"]
        j.source_position = state["tracker"]
        _apply_kafka_sinks(j, flags, state["sinks"])
        _kafka_loop(j, state["events"], flags, profile)
        return 0

    def _on_restart(exc: Exception, next_attempt: int) -> None:
        print(
            f"job failure ({type(exc).__name__}: {exc}); "
            f"restart {next_attempt - 1}/{attempts}",
            file=sys.stderr,
        )
        from omldm_tpu.runtime.recovery import recover_job

        new_job, _restored_from = recover_job(state["job"], ckpt_floor)
        if new_job.source_position is None:
            # fresh incarnation: data streams continue from the
            # live position (no replay on a live source), but the
            # CONTROL stream rewinds to the beginning — a
            # fresh-state job must re-consume Create/Update/Delete
            # requests to rebuild its topology (the reference's
            # topology is part of the submitted job graph; here it
            # is request-driven). Dropping the key makes the
            # reconnect seek those partitions to the beginning.
            position = dict(state["tracker"])
            from omldm_tpu.runtime.kafka_io import DEFAULT_TOPICS

            for key in list(position):
                if DEFAULT_TOPICS.get(key[0]) == REQUEST_STREAM:
                    del position[key]
            new_job.source_position = position
        tracker = dict(new_job.source_position)
        # close the abandoned clients: restarts must not leak
        # broker connections / fetcher threads
        state["sinks"].close()
        new_events, new_sinks = connect_kafka(
            flags["kafkaBrokers"],
            position=tracker,
            tracker=tracker,
            retry=connect_retry,
            send_retry=send_retry,
            pause_when=_pause_when,
        )
        state.update(
            job=new_job, events=new_events, sinks=new_sinks, tracker=tracker
        )
        pause_ref["job"] = new_job

    try:
        # fixed-delay restart strategy over the whole live loop —
        # RestartStrategies.fixedDelayRestart(attempts, delay) semantics
        return with_backoff(
            _attempt,
            attempts=attempts + 1,
            base_delay=delay_s,
            growth=1.0,
            retry_on=(Exception,),
            on_retry=_on_restart,
        )
    finally:
        if profile["tracing"]:
            import jax

            jax.profiler.stop_trace()


def _run_replay(job: StreamJob, flags: Dict[str, str], make_events) -> None:
    """Replay a deterministic source; ``--restartAttempts N`` opts into
    supervised recovery (Flink's fixed-delay restart strategy: restore the
    latest checkpoint — pass ``--checkpointing`` for stateful recovery —
    and resume the replay at the snapshot's event offset)."""
    attempts = int(flags.get("restartAttempts", "0"))
    if attempts > 0:
        from omldm_tpu.runtime.recovery import JobSupervisor, replayable

        JobSupervisor(
            job,
            replayable(make_events),
            max_restarts=attempts,
            restart_delay_s=float(flags.get("restartDelayMs", "0")) / 1000.0,
        ).run()
    else:
        job.run(make_events())


def _try_fused_run(job: StreamJob, flags: Dict[str, str]) -> bool:
    """The fastest file route: requests replayed up front, then the training
    file consumed by the fused C parse->holdout->stage loop
    (StreamJob.run_file_fused). Taken only when the per-event loop would
    have nothing else to schedule — a single SPMD-plane pipeline, a
    training file as the only data source, no checkpointing (the event loop
    owns maybe_save), no forecasting/file sinks racing the stream. Falls
    back to the packed event route otherwise; requests stay processed (the
    packed route coarsens request/data interleaving the same way)."""
    if TRAINING_STREAM not in flags:
        return False
    if flags.get("fastIngest", "auto") == "false":
        return False
    if flags.get("fusedIngest", "auto") == "false":
        return False
    if job.checkpoint_manager is not None:
        return False
    if int(flags.get("restartAttempts", "0")) > 0:
        return False  # supervised recovery wraps the event loop, not this
    if any(
        t in flags for t in _STREAMS if t not in (TRAINING_STREAM, REQUEST_STREAM)
    ):
        return False
    spec = _stream_spec(flags)
    sparse = False
    if spec is None:
        # sparse pipelines can't use the dense packed batcher, but they DO
        # have a fused route (SparseSPMDBridge.ingest_file): resolve the
        # width from a sparse Create instead
        spec = _sparse_stream_spec(flags)
        sparse = spec is not None
    if spec is None:
        return False
    if REQUEST_STREAM in flags:
        for stream, line in file_events(flags[REQUEST_STREAM], REQUEST_STREAM):
            job.process_event(stream, line)
        # consumed here either way: the fallback event route must not
        # replay them a second time. The packed fallback still needs the
        # width the requests pinned, so stash the resolved spec — except
        # for sparse jobs, whose fallback is the per-record route (the
        # dense packed batcher cannot feed them).
        del flags[REQUEST_STREAM]
        if sparse:
            # the dense packed batcher must NOT pick these jobs up on
            # fallback (it would infer a dense width from the data);
            # the marker sends them down the per-record route
            flags["__sparseStream__"] = "1"
        else:
            flags["__streamSpec__"] = f"{spec[0]},{spec[1]}"
    job.ensure_deployed(spec[0])
    # sharded ingest plane (--ingest / JobConfig.ingest): dense jobs only
    # (the parser shards run the dense packed batcher); host-plane and
    # multi-pipeline jobs are fine — blocks replay through the packed
    # event route, in stream order
    if job.ingest_cfg is not None and not sparse:
        if job.run_file_sharded(
            flags[TRAINING_STREAM], dim=spec[0], hash_dims=spec[1]
        ):
            job.terminate()
            return True
        return False
    if job.fused_file_bridge() is None:
        return False  # requests stay processed; packed route resumes
    job.run_file_fused(flags[TRAINING_STREAM])
    job.terminate()
    return True


def _sparse_stream_spec(flags: Dict[str, str]) -> Optional[Tuple[int, int]]:
    """(total feature dim, 0) from the first SPARSE Create/Update — the
    fused sparse route needs the width up front like the packed one."""
    from omldm_tpu.api.requests import Request, RequestType

    if REQUEST_STREAM not in flags:
        return None
    try:
        for _, line in file_events(flags[REQUEST_STREAM], REQUEST_STREAM):
            req = Request.from_json(line)
            if req is None or req.request not in (
                RequestType.CREATE, RequestType.UPDATE
            ):
                continue
            ds = req.learner.data_structure if req.learner else None
            if ds and ds.get("sparse") and "nFeatures" in ds:
                return int(ds["nFeatures"]), 0
            return None
    except OSError:
        return None
    return None


def _stream_spec(flags: Dict[str, str]) -> Optional[Tuple[int, int]]:
    """(total feature dim, hash_dims) for the packed ingest path: from the
    first Create/Update request carrying nFeatures, else inferred from the
    first training record (the reference sizes models lazily on the first
    record; here the packed batcher needs the width up front)."""
    from omldm_tpu.api.data import DataInstance
    from omldm_tpu.api.requests import Request, RequestType
    from omldm_tpu.runtime.vectorizer import Vectorizer

    if "__sparseStream__" in flags:
        return None  # sparse pipelines featurize per record (see below)
    if "__streamSpec__" in flags:  # resolved earlier by the fused route
        dim, hash_dims = flags["__streamSpec__"].split(",")
        return int(dim), int(hash_dims)
    if REQUEST_STREAM in flags:
        try:
            for _, line in file_events(flags[REQUEST_STREAM], REQUEST_STREAM):
                req = Request.from_json(line)
                if req is None or req.request not in (
                    RequestType.CREATE, RequestType.UPDATE
                ):
                    continue
                hash_dims = int(
                    req.training_configuration.extra.get("hashDims", 0)
                )
                ds = req.learner.data_structure if req.learner else None
                if ds and ds.get("sparse"):
                    # sparse pipelines featurize per record into padded COO
                    # (SparseVectorizer); the dense C++ block parser cannot
                    # feed them a wide hashed index space
                    return None
                if ds and "nFeatures" in ds:
                    return int(ds["nFeatures"]) + hash_dims, hash_dims
                # first Create without an explicit width: infer from data
                for _, dline in file_events(
                    flags[TRAINING_STREAM], TRAINING_STREAM
                ):
                    inst = DataInstance.from_json(dline)
                    if inst is not None:
                        return Vectorizer.infer_dim(inst, hash_dims), hash_dims
                return None
        except OSError:
            return None
    try:
        for _, dline in file_events(flags[TRAINING_STREAM], TRAINING_STREAM):
            inst = DataInstance.from_json(dline)
            if inst is not None:
                return Vectorizer.infer_dim(inst, 0), 0
    except OSError:
        return None
    return None


def _packed_training_source(flags: Dict[str, str]):
    """The training file as PACKED_STREAM events: C++ bulk parse ->
    (x, y, op) blocks, prefetched one block ahead of the device feed.
    Returns None when the width can't be pinned or (in auto mode) the
    native parser is unavailable — callers fall back to per-record JSON."""
    from omldm_tpu.ops.native import fast_parser_available
    from omldm_tpu.runtime.fast_ingest import iter_file_batches
    from omldm_tpu.runtime.prefetch import prefetch

    spec = _stream_spec(flags)
    if spec is None:
        return None
    if flags.get("fastIngest", "auto") != "true" and not fast_parser_available():
        return None
    dim, hash_dims = spec
    batches = iter_file_batches(
        flags[TRAINING_STREAM],
        dim,
        int(flags.get("ingestBatch", "8192")),
        hash_dims,
    )
    depth = int(flags.get("prefetchDepth", "2"))
    return ((PACKED_STREAM, b) for b in prefetch(batches, depth))


if __name__ == "__main__":
    sys.exit(main())
