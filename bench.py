"""Benchmark: end-to-end streaming training throughput (BASELINE.md config 1).

The PRIMARY metric is the honest whole-job number: JSON bytes -> trained
parameters through the real CLI ingest route (C++ block parse -> prefetch
thread -> packed holdout/staging -> chained SPMD device steps), the same
path `python -m omldm_tpu --trainingData file.jsonl` takes. This maps to
the reference's whole-job throughput (Job.scala:42-70 ->
FlinkSpoke.scala:92-107 per-record hot loop, which it drives at
parallelism 16 on a 4C/8T workstation, hs_err_pid77107.log:21).

In this environment the TPU sits behind a network tunnel that serializes
every host->device byte through a remote RPC (~15-20 MB/s effective, vs
>10 GB/s PCIe/DMA on any real host), so the benchmark decomposes the run
into three directly-measured components (see
benchmarks/run_benchmarks.py:bench_e2e_stream):

- raw:    full run including the tunnel (reported as a field);
- host:   the identical pipeline with the device stubbed (parse ceiling);
- device: the same chained launches on device-resident stages.

``value`` is the MEASURED wall-clock of a double-buffered overlapped run
(SPMDBridge.ingest_file_overlapped): the C parse thread fills stage k+1
while the dispatch thread trains stage k through a device stub calibrated
to the measured per-stage device time — i.e. the pipeline bottleneck
n / max(t_host, t_device) observed end to end, not modeled. The bound,
the raw tunnel runs (serial and overlapped), and all components are
reported alongside.

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
computed against a 100k examples/sec proxy — a generous estimate of the
reference's whole-job throughput at parallelism 16 on its workstation —
i.e. vs_baseline = value / 100_000.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))


def _ensure_reachable_backend() -> str:
    """The axon TPU tunnel can WEDGE (client init hangs instead of
    erroring); probe it in a killable subprocess and fall back to CPU so
    the benchmark always produces its JSON line. Returns the TRUE
    platform the run will execute on (``jax.default_backend()``), not a
    reachability verdict — a reachable-but-CPU-only jax is still an
    off-accelerator run and must be stamped as one."""
    import subprocess

    try:
        proc = subprocess.run(
            [
                sys.executable, "-c",
                "import jax; jax.devices(); print('bk:'"
                " + jax.default_backend())",
            ],
            capture_output=True, text=True, timeout=150,
        )
        if proc.returncode == 0:
            for line in proc.stdout.splitlines():
                if line.startswith("bk:"):
                    return line[3:].strip()
    except subprocess.TimeoutExpired:
        pass
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu-fallback (accelerator unreachable)"


# exit code when the run executed off-accelerator (the tunnel wedged and
# we fell back, OR jax's true default backend is plain CPU): the JSON
# record still prints (the numbers are real, the backend field says what
# they measure), but the process exits nonzero so a chip harness that
# EXPECTED accelerator numbers fails loudly instead of silently recording
# host-fallback figures as if they were device runs
FALLBACK_EXIT = 3

ACCELERATOR_BACKENDS = ("tpu", "gpu", "cuda", "rocm")


def main() -> int:
    backend = _ensure_reachable_backend()
    from run_benchmarks import bench_e2e_stream

    _, measured, extra = bench_e2e_stream(n_records=1_000_000)
    extra["backend"] = backend
    print(
        json.dumps(
            {
                "metric": (
                    "e2e streaming train throughput, JSON bytes -> trained "
                    "params (measured double-buffered overlapped run)"
                ),
                "value": round(measured, 1),
                "unit": "examples/sec",
                "vs_baseline": round(measured / 100_000.0, 3),
                **extra,
            }
        )
    )
    if backend not in ACCELERATOR_BACKENDS:
        print(
            f"WARNING: off-accelerator run (backend={backend}); numbers "
            "are host-pipeline figures, exiting nonzero",
            file=sys.stderr,
        )
        return FALLBACK_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
