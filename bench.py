"""Benchmark: HIGGS-shaped online logistic regression, examples/sec/chip.

BASELINE.md config 1 ("Online logistic regression, HIGGS binary"): a
28-feature binary-classification stream through the StandardScaler +
logistic-regression (Softmax, K=2) pipeline — the same workload the
reference trains per-record on the JVM (MLPipeline.pipePoint ->
learner.fit, hs_err_pid77107.log:109-113). Here the whole pipeline step
(scaler update + transform + LR gradient step + loss) is one jitted XLA
program consuming fixed-shape micro-batches from host memory (streaming
ingest modeled by feeding per-step numpy batches).

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
computed against a 100k examples/sec proxy — a generous estimate of the
reference's whole-job throughput at parallelism 16 on its 4C/8T workstation
(hs_err_pid77107.log:21), i.e. vs_baseline = measured / 100_000.
"""

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
    from omldm_tpu.pipelines import MLPipeline

    dim = 28
    batch = 4096
    pipe = MLPipeline(
        LearnerSpec("Softmax", hyper_parameters={"learningRate": 0.05, "nClasses": 2}),
        [PreprocessorSpec("StandardScaler")],
        dim=dim,
        rng=jax.random.PRNGKey(0),
    )

    rng = np.random.RandomState(0)
    w = rng.randn(dim)
    n_stage = 32  # distinct staged batches cycled to model streaming ingest;
    # batches are pre-staged on device (double-buffered prefetch): in this
    # environment the chip sits behind a network tunnel whose host->device
    # bandwidth would otherwise measure the tunnel, not the framework
    xs = rng.randn(n_stage, batch, dim).astype(np.float32)
    ys = (xs @ w > 0).astype(np.float32)
    masks = np.ones((n_stage, batch), np.float32)
    counts = masks.sum(axis=1)
    xs_d, ys_d, masks_d = (jax.device_put(a) for a in (xs, ys, masks))

    # fit_many: the T staged micro-batches train as ONE lax.scan program —
    # the device never waits on host dispatch between steps (the same chained
    # path the protocol workers use to drain a training backlog,
    # WorkerNode.drain_blocked)
    # warmup / compile
    pipe.fit_many(xs_d, ys_d, masks_d, valid_counts=counts)
    jax.block_until_ready(pipe.state["params"])

    rounds = 20
    t0 = time.perf_counter()
    for _ in range(rounds):
        pipe.fit_many(xs_d, ys_d, masks_d, valid_counts=counts)
    jax.block_until_ready(pipe.state["params"])
    dt = time.perf_counter() - t0

    examples_per_sec = rounds * n_stage * batch / dt
    print(
        json.dumps(
            {
                "metric": "HIGGS-shaped online LR examples/sec/chip",
                "value": round(examples_per_sec, 1),
                "unit": "examples/sec",
                "vs_baseline": round(examples_per_sec / 100_000.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
